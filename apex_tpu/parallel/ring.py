"""Ring attention & Ulysses — sequence/context parallelism.

The reference has **no** long-context machinery (SURVEY.md §5: attention
kernels are full-sequence-on-device, `apex/contrib/csrc/multihead_attn/
softmax.h`); a TPU framework at this scale owes it. Two standard schemes
over a ``seq`` mesh axis:

- :func:`ring_attention` — q/k/v sharded on sequence; k/v blocks rotate
  around the ring via ``ppermute`` while each device merges blockwise
  partial attention (out, lse) pairs in log space. Memory O(S_local·D),
  communication N-1 ppermute hops riding ICI neighbors. The per-block
  compute is the fused flash kernel (apex_tpu.ops.attention), whose
  lse-differentiable variant makes the whole ring a plain composition —
  autodiff derives the reverse ring (the transpose of ppermute is the
  inverse rotation), no hand-written backward.
- :func:`ulysses_attention` — all-to-all re-shard: sequence-sharded
  q/k/v become head-sharded with the full sequence per device, local
  flash attention runs unsharded, and a second all-to-all restores
  sequence sharding. One collective pair, best when heads % devices == 0.

Causality across shards rides the kernels' ``causal_offset`` (a traced
scalar, derived from the device's ring position): query i attends key j
iff ``i + offset >= j`` with ``offset = my·sq − src·sk``. The kernel
call stays identical on every device (SPMD-friendly: no data-dependent
branching on rank), no O(S²) hop bias is ever materialized, and the hop
runs the native-layout kernel path at full tile sizes. Geometries the
native path can't serve fall back to an internally-built additive mask
(the previous behavior).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import (DROPOUT_TILE, flash_attention,
                                    flash_attention_lse)

NEG_INF = -1e30


def _merge(o, lse, o_i, lse_i):
    """Merge normalized partial attention (out, lse) pairs in log space."""
    lse_c = jnp.logaddexp(lse, lse_i)
    w = jnp.exp(lse - lse_c)       # (B, H, S)
    w_i = jnp.exp(lse_i - lse_c)
    expand = lambda t: jnp.swapaxes(t, 1, 2)[..., None]  # (B, S, H, 1)
    o_c = o * expand(w) + o_i * expand(w_i)
    return o_c, lse_c


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None,
                   dropout_rate: float = 0.0, dropout_seed=None):
    """Blockwise-exact attention over a sequence-sharded ring.

    q/k/v: (B, S_local, H, D), the local sequence shard of each device on
    ``axis_name`` (global sequence = concatenation in axis order).
    Returns the local output shard (B, S_local, H, D).

    With ``dropout_rate`` > 0 the softmax dropout mask is BITWISE the
    mask the single-device fast path would draw for the gathered
    sequence and the same seed: the counter-based hash keys on global
    (batch·head, q-block, k-block) coordinates, and each hop shifts its
    block coordinates by its ring position (the ``causal_offset`` trick
    applied to the dropout hash). Requires the local shard lengths to
    be multiples of the 512 dropout tile so local blocks align with the
    global blocking — anything else raises rather than silently drawing
    a different mask. The log-space merge stays exact under dropout:
    partial outputs carry the masked probabilities while lse carries
    the undropped partition, which is precisely the global dropout
    attention when combined.
    """
    world = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    sq = q.shape[1]
    sk = k.shape[1]
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        if sq % DROPOUT_TILE or sk % DROPOUT_TILE:
            raise ValueError(
                f"ring dropout needs local shard lengths that are "
                f"multiples of the {DROPOUT_TILE} dropout tile (got "
                f"Sq={sq}, Sk={sk}): the mask is a function of the "
                f"global block decomposition and would not match the "
                f"single-device mask")
    nqb, nkb = sq // DROPOUT_TILE, sk // DROPOUT_TILE

    perm = [(i, (i + 1) % world) for i in range(world)]

    def block(q, kv_k, kv_v, src):
        kw = {}
        if dropout_rate > 0.0:
            kw = dict(dropout_rate=dropout_rate,
                      dropout_seed=dropout_seed,
                      dropout_block_offset=jnp.stack(
                          [my * nqb, src * nkb]).astype(jnp.int32))
        if causal:
            # global causality as a traced offset — no hop bias tensor
            off = my * sq - src * sk
            return flash_attention_lse(q, kv_k, kv_v, scale=scale,
                                       causal=True, causal_offset=off,
                                       **kw)
        return flash_attention_lse(q, kv_k, kv_v, scale=scale, **kw)

    o, lse = block(q, k, v, my)
    cur_k, cur_v = k, v
    for step in range(1, world):
        cur_k = jax.lax.ppermute(cur_k, axis_name, perm)
        cur_v = jax.lax.ppermute(cur_v, axis_name, perm)
        src = (my - step) % world
        o_i, lse_i = block(q, cur_k, cur_v, src)
        if causal:
            # fully-masked blocks produce lse == log(safe) garbage only on
            # rows with zero mass; their lse is ~NEG_INF so merging is a
            # no-op — but guard explicitly for src > my (whole block off)
            off = src > my
            lse_i = jnp.where(off, NEG_INF, lse_i)
        o, lse = _merge(o, lse, o_i, lse_i)
    return o


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None,
                      dropout_rate: float = 0.0, dropout_seed=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Re-shards (seq-sharded, all heads) → (all seq, head-sharded), runs
    local fused attention, and restores. Requires H % axis_size == 0.

    **Softmax dropout is a deliberate, load-bearing refusal** (tested:
    ``tests/test_ring_attention.py::test_ulysses_dropout_raises``). The
    fused kernels' keep-mask is a counter-based hash of the score
    element's *global* grid coordinates, and its batch·head term is the
    kernel grid row ``b·H + h`` (``ops.attention._keep_mask``). After
    the Ulysses head re-shard, device d computes head-row ``b·(H/w) +
    h_local`` where the single-device mask needs ``b·H + d·(H/w) +
    h_local`` — not an affine shift of the local row (the ``H/w → H``
    stride change mixes batch and head), so unlike the sequence-shard
    case there is no ``dropout_block_offset``-style traced offset that
    repairs it; the kernels would need a head-reshard coordinate remap
    in all four mask sites plus the dense bias-grad replica. Until
    then, a silently-local mask would break train/eval parity with the
    single-device model — refusing loudly is the correct behavior.
    """
    if dropout_rate > 0.0:
        raise NotImplementedError(
            "ulysses_attention does not support softmax dropout: after "
            "the all-to-all head re-shard the kernels' batch-head mask "
            "coordinate is local (b*H_local + h_local, stride H_local) "
            "while the single-device mask hashes b*H + h_global (stride "
            "H) — the masks would silently diverge from the "
            "single-device model. Use ring_attention(q, k, v, "
            f"{axis_name!r}, dropout_rate={dropout_rate}, "
            "dropout_seed=...) instead: its sequence-block offsets keep "
            "the mask bitwise-identical to the single-device kernel "
            "(docs/parallel.md#ulysses-dropout).")
    del dropout_seed
    world = jax.lax.axis_size(axis_name)
    h = q.shape[2]
    if h % world:
        raise ValueError(f"heads {h} not divisible by axis size {world}")

    def scatter_heads(t):
        # (B, S/w, H, D) -> (B, S, H/w, D)
        return jax.lax.all_to_all(t, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def gather_heads(t):
        return jax.lax.all_to_all(t, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qf, kf, vf = map(scatter_heads, (q, k, v))
    of = flash_attention(qf, kf, vf, causal=causal, scale=scale)
    return gather_heads(of)
