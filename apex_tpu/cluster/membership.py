"""Cluster membership & generation fencing over a shared filesystem.

The resilience arc (guard ladder, watchdog escalation, ``elastic_run``)
made a *single process* survive faults — but every one of those
decisions is per-rank, and nothing stops a stale "zombie" rank (paused,
preempted-then-resumed, or racing a restart) from writing into the
shared checkpoint directory a new incarnation of the job is already
using. This module is the dynamic complement of apexlint's APX201
static congruence check: cross-rank agreement at *runtime*, built from
the two shared-fs primitives the repo already trusts —
one-file-per-rank writes (the heartbeat/ckpt pattern) and a
commit-record-written-LAST atomic rename (the manifest pattern).

Two pieces:

- **leases** (:class:`LeaseWriter`): each rank periodically renews a
  small per-rank lease file carrying ``{rank, generation, expires_at}``.
  A rank whose lease expired is *dead as far as the cluster is
  concerned* — even if the process later resumes (SIGSTOP/SIGCONT, a
  VM migration pause), it must re-join and re-validate its generation
  before touching shared state. No cross-rank writes, torn-tail
  tolerant reads, jittered-retry appends (:mod:`apex_tpu.utils.backoff`).

- **generation** (:func:`bump_generation` / :func:`read_generation`):
  a monotonic epoch counter committed as one immutable
  ``generation.{n:08d}.json`` file per epoch, published by exclusive
  hard-link (temp→fsync→link) — the *filename* is the commit, so the
  publish is a true compare-and-swap: two racers for the same epoch
  cannot both land, and a stalled writer from an old round cannot
  roll the committed epoch backwards (its target filename already
  exists). Readers take the max epoch present; epoch files are never
  deleted. Every recovery decision (coordinated rewind, elastic
  relaunch) bumps it; every checkpoint write, heartbeat, and
  escalation event carries its generation as a **fence token**, and the
  checkpoint format refuses commits (and retention refuses deletes)
  bearing a stale one — so a zombie rank from generation N cannot
  corrupt generation N+1's run.

:class:`ClusterMembership` ties both together and is the ``fence=``
object :class:`apex_tpu.ckpt.CheckpointManager` accepts; events are
``kind="cluster_*"`` JSONL on the cluster channel
(``MetricsLogger(cluster_sink=...)``;
``check_metrics_schema.py --kind cluster`` validates).
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import secrets
import time
from typing import Callable, Dict, List, Optional

from apex_tpu.utils.backoff import backoff_sleep
from apex_tpu.utils.fsio import fsync_dir, write_atomic

__all__ = ["ClusterMembership", "LeaseWriter", "StaleGenerationError",
           "read_generation", "read_generation_record", "bump_generation",
           "read_leases", "lease_path", "gc_stale_leases",
           "gc_stale_intents", "cluster_token", "GENERATION_PREFIX",
           "generation_path", "INTENT_PREFIX"]

#: immutable per-epoch commit files (``generation.00000003.json``) —
#: the FILENAME is the commit (published by exclusive create), the
#: content is forensic metadata; never deleted (a deleted epoch would
#: reopen the rollback race the scheme exists to close)
GENERATION_PREFIX = "generation."
TOKEN_FILE = "cluster_token"
_LEASE_PREFIX = "lease.rank"
#: recovery-intent files (``intent.g00000003.rank00001.json``) — owned
#: by :mod:`apex_tpu.cluster.coordinator`, named here so the relaunch
#: hygiene pass can garbage-collect resolved rounds' files
INTENT_PREFIX = "intent.g"


class StaleGenerationError(RuntimeError):
    """A fence refusal: an actor carrying generation ``generation``
    tried to mutate shared state owned by ``current`` > generation.
    The actor is a zombie of a previous incarnation — the only safe
    response is to stop writing (and usually to exit)."""

    def __init__(self, what: str, *, generation: int, current: int,
                 detail: str = ""):
        super().__init__(
            f"stale generation fence: refusing {what} from generation "
            f"{generation} — the cluster is at generation {current}"
            + (f" ({detail})" if detail else "")
            + "; this process is a zombie of a previous incarnation "
              "(paused, preempted-then-resumed, or racing a restart) "
              "and must not touch shared state")
        self.what = what
        self.generation = int(generation)
        self.current = int(current)


def _rank_default() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", "0"))


def _write_atomic(path: str, data: bytes) -> None:
    """temp → fsync → rename (:func:`apex_tpu.utils.fsio.write_atomic`
    — readers never see a torn record, the rename IS the commit point);
    the pid-qualified temp keeps concurrent writers of the SAME path
    (e.g. two ranks racing a generation bump) off each other's temp."""
    write_atomic(path, data, tmp_suffix=f".{os.getpid()}.tmp")


def _read_json_retry(path: str, *, attempts: int = 3) -> Optional[Dict]:
    """Read one atomic JSON record, absorbing the rename-visibility /
    brief-staleness window a networked fs shows racing readers. None
    when genuinely absent (or unreadable after ``attempts``)."""
    for k in range(max(int(attempts), 1)):
        try:
            with open(path) as f:
                rec = json.load(f)
            return rec if isinstance(rec, dict) else None
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            if k + 1 < attempts:
                backoff_sleep(k, base_s=0.02, cap_s=0.2)
    return None


# --- the shared signing token -------------------------------------------------

def cluster_token(directory: str) -> str:
    """The cluster's shared signing secret (hex), created on first use.

    Intents and leases are MAC'd with it (HMAC-SHA256) so a reader can
    tell a record written by a member of *this* cluster directory from
    a torn write, a stray file, or a rank pointed at the wrong run —
    integrity against accidents, not an adversary (anyone who can read
    the shared directory can read the token too)."""
    path = os.path.join(directory, TOKEN_FILE)
    rec = _read_json_retry(path)
    if rec and isinstance(rec.get("token"), str):
        return rec["token"]
    os.makedirs(directory, exist_ok=True)
    token = secrets.token_hex(16)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump({"token": token, "wall_time": time.time()}, f)
        f.flush()
        os.fsync(f.fileno())
    try:
        # first writer wins: link-style exclusive create, so two ranks
        # racing the very first join agree on ONE token
        os.link(tmp, path)
    except FileExistsError:
        pass
    except OSError:
        # filesystems without hard links: O_EXCL create keeps
        # first-writer-wins (an exists()-then-replace fallback would
        # be a TOCTOU — two first-joiners could adopt DIFFERENT
        # tokens and split the cluster into two MAC domains)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            with os.fdopen(fd, "w") as f:
                json.dump({"token": token, "wall_time": time.time()}, f)
                f.flush()
                os.fsync(f.fileno())
        except FileExistsError:
            pass
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass
    rec = _read_json_retry(path)
    if not rec or not isinstance(rec.get("token"), str):
        raise OSError(f"could not establish cluster token at {path}")
    return rec["token"]


def sign_payload(token: str, payload: Dict) -> str:
    """Deterministic HMAC over a canonical JSON encoding."""
    canon = json.dumps(payload, sort_keys=True,
                       separators=(",", ":")).encode()
    return hmac.new(bytes.fromhex(token), canon,
                    hashlib.sha256).hexdigest()


def mac_ok(token: str, rec: Dict) -> bool:
    """Does ``rec``'s ``mac`` verify against the cluster token? A
    record that fails is a torn write, a stray/foreign file, or
    tampering — never counted, always eligible for gc."""
    mac = rec.get("mac")
    if not isinstance(mac, str):
        return False
    body = {k: v for k, v in rec.items() if k != "mac"}
    try:
        return hmac.compare_digest(mac, sign_payload(token, body))
    except (TypeError, ValueError):
        return False


# --- generation ---------------------------------------------------------------

def generation_path(directory: str, generation: int) -> str:
    return os.path.join(
        directory, f"{GENERATION_PREFIX}{int(generation):08d}.json")


def _committed_epochs(directory: str) -> List[int]:
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        if not (name.startswith(GENERATION_PREFIX)
                and name.endswith(".json")):
            continue
        mid = name[len(GENERATION_PREFIX):-len(".json")]
        if mid.isdigit():
            out.append(int(mid))
    return sorted(out)


def read_generation_record(directory: str) -> Dict:
    """The committed generation record — the MAX epoch file present
    (``{"generation": 0}`` when the cluster directory is fresh —
    generation 0 is the implicit first epoch, so a run needs no
    bootstrap write). The filename is authoritative: an epoch file
    with unreadable content (the brief torn window of the no-hardlink
    fallback) still commits its epoch."""
    epochs = _committed_epochs(directory)
    if not epochs:
        return {"generation": 0}
    n = epochs[-1]
    rec = _read_json_retry(generation_path(directory, n))
    if not rec or rec.get("generation") != n:
        return {"generation": n}
    return rec


def read_generation(directory: str) -> int:
    return int(read_generation_record(directory)["generation"])


def bump_generation(directory: str, *, rank: Optional[int] = None,
                    reason: str = "", expect: Optional[int] = None) -> int:
    """Commit generation ``current + 1`` as a new immutable epoch file,
    published by exclusive create — a true CAS: of N racers for the
    same next epoch exactly one lands, the rest get
    :class:`StaleGenerationError`; and a writer stalled since an OLD
    round cannot roll the committed epoch backwards, because its
    target filename already exists however long it slept between its
    read and its publish.

    ``expect`` is the optimistic-concurrency guard for coordinated
    bumps: when set and the on-disk generation already moved past it,
    raise :class:`StaleGenerationError` instead of double-bumping —
    the caller lost the race (another leader already fenced this
    epoch) and must re-read rather than stack epochs. (The exclusive
    create below enforces the same property even WITHOUT ``expect`` —
    the pre-check just gives a cheaper, better-attributed refusal.)
    """
    os.makedirs(directory, exist_ok=True)
    current = read_generation(directory)
    if expect is not None and current != int(expect):
        raise StaleGenerationError(
            "generation bump", generation=int(expect), current=current,
            detail="another rank already bumped this epoch")
    new = current + 1
    rec = {"generation": new, "prev_generation": current,
           "committed_by_rank": (_rank_default() if rank is None
                                 else int(rank)),
           "reason": reason or None, "wall_time": time.time()}
    data = json.dumps(rec).encode()
    path = generation_path(directory, new)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    try:
        # exclusive hard-link publish: content already durable, the
        # link IS the commit and exactly one racer's succeeds
        os.link(tmp, path)
    except FileExistsError:
        raise StaleGenerationError(
            "generation bump", generation=current,
            current=read_generation(directory),
            detail="another rank already bumped this epoch")
    except OSError:
        # filesystems without hard links: O_EXCL create keeps the
        # exactly-one-winner property; readers may glimpse torn
        # CONTENT for an instant, but the filename already committed
        # the epoch (read_generation_record tolerates that)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            raise StaleGenerationError(
                "generation bump", generation=current,
                current=read_generation(directory),
                detail="another rank already bumped this epoch")
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass
    fsync_dir(directory)
    return new


# --- leases -------------------------------------------------------------------

def lease_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"{_LEASE_PREFIX}{int(rank):05d}.json")


def read_leases(directory: str, *,
                token: Optional[str] = None) -> Dict[int, Dict]:
    """``{rank: lease record}`` over every lease file present.
    Torn/corrupt files are skipped (a reader racing an atomic replace
    on a laggy fs) — the rank simply reads as lease-less until the
    next renewal lands. ``token`` additionally drops records whose
    MAC does not verify (a stray/foreign file must not read as a
    member — a phantom rank would stall every recovery barrier)."""
    out: Dict[int, Dict] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(_LEASE_PREFIX) and name.endswith(".json")):
            continue
        try:
            rank = int(name[len(_LEASE_PREFIX):-len(".json")])
        except ValueError:
            continue
        rec = _read_json_retry(os.path.join(directory, name), attempts=1)
        if rec is None:
            continue
        if token is not None and not mac_ok(token, rec):
            continue
        out[rank] = rec
    return out


def gc_stale_leases(directory: str, current_generation: int, *,
                    token: Optional[str] = None) -> List[str]:
    """Remove lease files from generations older than ``current`` —
    the relaunch hygiene pass: a dead rank's last lease must not read
    as a live (or freshly-dead) member of the NEW epoch forever. With
    ``token``, files whose MAC fails verification are removed too
    (they can never count as members, only clutter the table).
    Returns removed paths."""
    removed: List[str] = []
    for rank, rec in read_leases(directory).items():
        gen = rec.get("generation")
        fresh = isinstance(gen, int) and gen >= int(current_generation)
        verified = token is None or mac_ok(token, rec)
        if fresh and verified:
            continue
        p = lease_path(directory, rank)
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    return removed


def gc_stale_intents(directory: str,
                     current_generation: int) -> List[str]:
    """Remove recovery-intent files of generations older than
    ``current`` — a resolved round's files are inert the moment the
    leader bumps, but on a long-running job they would otherwise
    accumulate forever under the per-step ``pending()`` listdir.
    Returns removed paths."""
    removed: List[str] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return removed
    for name in names:
        if not (name.startswith(INTENT_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            gen = int(name[len(INTENT_PREFIX):].split(".", 1)[0])
        except ValueError:
            continue
        if gen >= int(current_generation):
            continue
        p = os.path.join(directory, name)
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    return removed


class LeaseWriter:
    """This rank's membership lease: acquire → renew per step → release.

    A lease is one atomically-replaced JSON file ``{rank, generation,
    wall_time, expires_at, pid, n_renewals, mac}``; ``expires_at``
    (wall clock + ``ttl_s``) is the death certificate readers act on —
    a crash needs no cleanup, the lease just stops being renewed.
    Writes retry through the shared jittered backoff and then drop the
    renewal (a lost renewal must never break the train loop; the next
    one re-asserts liveness, and TTLs are sized >> one step)."""

    def __init__(self, directory: str, rank: Optional[int] = None, *,
                 ttl_s: float = 30.0, attempts: int = 3):
        self.directory = directory
        self.rank = _rank_default() if rank is None else int(rank)
        self.ttl_s = float(ttl_s)
        self.attempts = max(int(attempts), 1)
        os.makedirs(directory, exist_ok=True)
        #: cached once — the token is immutable after creation, and a
        #: per-renewal re-read would cost a shared-fs round trip per
        #: training step
        self.token = cluster_token(directory)
        self.path = lease_path(directory, self.rank)
        self.generation: Optional[int] = None
        self.n_renewals = 0
        self.n_dropped = 0

    def _record(self, *, expires_at: Optional[float] = None) -> Dict:
        now = time.time()
        payload = {
            "rank": self.rank, "generation": int(self.generation or 0),
            "wall_time": now,
            "expires_at": (now + self.ttl_s if expires_at is None
                           else float(expires_at)),
            "ttl_s": self.ttl_s, "pid": os.getpid(),
            "n_renewals": self.n_renewals,
        }
        payload["mac"] = sign_payload(self.token, payload)
        return payload

    def _write(self, rec: Dict) -> bool:
        data = json.dumps(rec).encode()
        for attempt in range(self.attempts):
            try:
                _write_atomic(self.path, data)
                return True
            except OSError:
                if attempt + 1 < self.attempts:
                    backoff_sleep(attempt, cap_s=0.2)
        self.n_dropped += 1
        return False

    def acquire(self, generation: int) -> bool:
        self.generation = int(generation)
        self.n_renewals = 0
        return self._write(self._record())

    def renew(self) -> bool:
        if self.generation is None:
            raise RuntimeError("renew() before acquire(generation)")
        self.n_renewals += 1
        return self._write(self._record())

    def release(self) -> None:
        try:
            os.remove(self.path)
        except OSError:
            pass

    def expire_now(self) -> bool:
        """Backdate this lease's expiry — the ``cluster:lease_expire``
        chaos site: the process is alive but the cluster must treat it
        as dead (exactly what a long SIGSTOP pause looks like from the
        outside)."""
        return self._write(self._record(expires_at=time.time() - 1.0))


def _lease_expired(rec: Dict, now: float) -> bool:
    exp = rec.get("expires_at")
    return not isinstance(exp, (int, float)) or now >= float(exp)


# --- the membership facade ----------------------------------------------------

class ClusterMembership:
    """Lease + generation for one rank, and the ``fence`` object the
    checkpoint layer consumes.

    ::

        member = cluster.ClusterMembership(cluster_dir,
                                           event_sink=logger.record_cluster)
        gen = member.join()
        mgr = ckpt.CheckpointManager(root, fence=member)
        for step, batch in ...:
            ...
            member.heartbeat()          # renew the lease

    The **fence contract**: :attr:`generation` is this process's fence
    token (fixed at :meth:`join`, advanced only by :meth:`bump` /
    :meth:`rejoin`), and :meth:`check` re-reads the *committed*
    generation from disk and raises :class:`StaleGenerationError` when
    the token is stale — which is how a resumed zombie discovers the
    world moved on, however long it was paused. Every refusal is
    emitted as a ``cluster_fence`` event *before* the raise (fencing
    events must survive the exit they usually precede — wire
    ``event_sink=logger.record_cluster``, the unbuffered channel).
    """

    def __init__(self, directory: str, *, rank: Optional[int] = None,
                 ttl_s: float = 30.0,
                 event_sink: Optional[Callable[[Dict], None]] = None):
        self.directory = directory
        self.rank = _rank_default() if rank is None else int(rank)
        self.event_sink = event_sink
        self.lease = LeaseWriter(directory, self.rank, ttl_s=ttl_s)
        self._generation: Optional[int] = None

    # -- events ----------------------------------------------------------------

    def _emit(self, event: Dict) -> None:
        if self.event_sink is None:
            return
        try:
            self.event_sink(dict(event, rank=self.rank,
                                 wall_time=time.time()))
        except Exception:
            pass              # telemetry must never break membership

    # -- lifecycle -------------------------------------------------------------

    @property
    def generation(self) -> int:
        """This process's fence token (0 before :meth:`join`)."""
        return 0 if self._generation is None else self._generation

    def join(self) -> int:
        """Read the committed generation and acquire this rank's lease
        under it. Returns the generation joined."""
        self._generation = read_generation(self.directory)
        self.lease.acquire(self._generation)
        self._emit({"kind": "cluster_lease", "action": "acquire",
                    "generation": self._generation,
                    "ttl_s": self.lease.ttl_s, "path": self.lease.path})
        return self._generation

    def heartbeat(self) -> bool:
        """Renew the lease (call at step cadence; a TTL is sized in
        steps). Not an event per renewal — that would be a per-step
        write amplification on the telemetry stream for zero forensic
        value; acquire/expire/release are the interesting edges."""
        if self._generation is None:
            self.join()
        return self.lease.renew()

    def leave(self) -> None:
        self.lease.release()
        self._emit({"kind": "cluster_lease", "action": "release",
                    "generation": self.generation,
                    "path": self.lease.path})

    def refresh(self) -> int:
        """Re-read the committed generation WITHOUT adopting it —
        observation only (the adoption path is :meth:`rejoin`, which is
        a deliberate act after recovery coordination)."""
        return read_generation(self.directory)

    def rejoin(self) -> int:
        """Adopt the current committed generation (post-coordination:
        the decision bumped it, survivors re-join under the new epoch)
        and re-acquire the lease under it."""
        new = self.join()
        self._emit({"kind": "cluster_generation", "action": "observe",
                    "generation": new, "reason": "rejoin",
                    "prev_generation": None})
        return new

    def bump(self, reason: str = "", *,
             expect: Optional[int] = None) -> int:
        """Commit the next generation (fencing out every holder of the
        old token) and adopt it. ``expect`` defaults to this member's
        own token — so a zombie cannot bump over an epoch it never
        belonged to."""
        prev = self.generation
        new = bump_generation(self.directory, rank=self.rank,
                              reason=reason,
                              expect=self.generation if expect is None
                              else expect)
        self._generation = new
        self.lease.acquire(new)
        self._emit({"kind": "cluster_generation", "action": "bump",
                    "generation": new, "prev_generation": prev,
                    "reason": reason or None})
        return new

    def claim_generation(self, generation: int) -> None:
        """Assert a LOCAL fence token without committing it — the
        ``cluster:split_brain`` chaos site: this rank now claims an
        epoch the cluster never agreed on, and every verifier
        (coordinator intents, fences on commit) must refuse it."""
        self._generation = int(generation)
        self.lease.acquire(self._generation)

    # -- liveness --------------------------------------------------------------

    def leases(self) -> Dict[int, Dict]:
        """MAC-verified lease table (stray/foreign files excluded)."""
        return read_leases(self.directory, token=self.lease.token)

    def alive_ranks(self, now: Optional[float] = None) -> List[int]:
        """Ranks holding an unexpired lease of the CURRENT committed
        generation."""
        now = time.time() if now is None else now
        cur = self.refresh()
        return sorted(r for r, rec in self.leases().items()
                      if rec.get("generation") == cur
                      and not _lease_expired(rec, now))

    def expired_ranks(self, now: Optional[float] = None) -> List[int]:
        """Ranks whose lease exists but expired — the dead-member
        signal that drives a coordinated shrink. Emits one
        ``cluster_lease`` ``action="expire"`` observation per call
        when any are found."""
        now = time.time() if now is None else now
        leases = self.leases()
        out = sorted(r for r, rec in leases.items()
                     if _lease_expired(rec, now))
        # a never-joined observer (elastic_run's controller) has no
        # fence token of its own — attribute its observations to the
        # COMMITTED epoch, not the placeholder 0
        gen = (self.generation if self._generation is not None
               else self.refresh())
        for r in out:
            exp = leases[r].get("expires_at")
            self._emit({"kind": "cluster_lease", "action": "expire",
                        "generation": gen,
                        "expires_at": (float(exp) if isinstance(
                            exp, (int, float)) else None),
                        "expired_rank": r})
        return out

    # -- the fence -------------------------------------------------------------

    def check(self, what: str = "commit", *,
              path: Optional[str] = None,
              step: Optional[int] = None) -> int:
        """Validate this process's fence token against the COMMITTED
        generation (re-read from disk — a zombie's cached view is
        exactly what cannot be trusted). Returns the current
        generation; raises :class:`StaleGenerationError` (after
        emitting the ``cluster_fence`` refusal) on ANY mismatch — a
        lower token is a zombie of a previous epoch, a higher one a
        split-brain claim the cluster never committed; neither may
        touch shared state."""
        current = self.refresh()
        if self.generation != current:
            action = {"commit": "refused_commit",
                      "write": "refused_write",
                      "delete": "refused_delete"}.get(what,
                                                      "refused_commit")
            self._emit({"kind": "cluster_fence", "action": action,
                        "generation": self.generation,
                        "current_generation": current, "what": what,
                        "path": path, "step": step, "reason": None})
            raise StaleGenerationError(
                what, generation=self.generation, current=current,
                detail=("the claimed generation was never committed "
                        "(split-brain)"
                        if self.generation > current else ""))
        return current

    # -- relaunch hygiene ------------------------------------------------------

    def gc_stale(self, *, heartbeat_dir: Optional[str] = None
                 ) -> List[str]:
        """Remove lease, recovery-intent and (when ``heartbeat_dir``
        is given) straggler heartbeat files left by older generations
        — see :func:`gc_stale_leases` / :func:`gc_stale_intents` /
        :func:`apex_tpu.trace.straggler.gc_stale_heartbeats`. Returns
        removed paths."""
        cur = self.refresh()
        removed = gc_stale_leases(self.directory, cur,
                                  token=self.lease.token)
        removed += gc_stale_intents(self.directory, cur)
        if heartbeat_dir is not None:
            from apex_tpu.trace.straggler import gc_stale_heartbeats
            removed += gc_stale_heartbeats(heartbeat_dir, cur)
        if removed:
            self._emit({"kind": "cluster_lease", "action": "gc",
                        "generation": cur, "n_removed": len(removed)})
        return removed
