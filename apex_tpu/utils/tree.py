"""Pytree utilities shared across the framework.

These are the functional equivalents of the reference's tensor-list plumbing
(`apex/multi_tensor_apply`, `apex/fp16_utils/fp16util.py`): where Apex walks
Python lists of tensors, apex_tpu maps over pytrees and lets XLA fuse.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def tree_cast(tree, dtype, predicate=None):
    """Cast every floating-point array leaf to ``dtype``.

    ``predicate(path, leaf)`` may veto the cast per-leaf (used for
    ``keep_batchnorm_fp32``-style exemptions). Non-float leaves and
    non-array leaves (None, strings, Python scalars — weak-typed in JAX)
    pass through. numpy arrays are cast like jax arrays so eager/host-side
    batches behave the same as traced ones.
    """
    if dtype is None:
        return tree

    def _cast(path, x):
        if not isinstance(x, (jax.Array, np.ndarray)):
            return x
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        if predicate is not None and not predicate(path, x):
            return x
        return jnp.asarray(x).astype(dtype) if isinstance(x, np.ndarray) \
            else x.astype(dtype)

    return jax.tree_util.tree_map_with_path(_cast, tree)


def tree_all_finite(tree):
    """Single boolean scalar: True iff every element of every leaf is finite.

    The on-device analogue of the reference's ``_overflow_buf`` (a GPU flag
    written by the multi-tensor kernels and read back with ``.item()``,
    `apex/amp/scaler.py:197-200`). Here the flag stays on device; step-skipping
    is data-dependent `jnp.where`, never a host sync.
    """
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    if not leaves:
        return jnp.bool_(True)
    finites = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(finites).all()


def tree_select(pred, on_true, on_false):
    """Elementwise ``jnp.where(pred, a, b)`` over two matching pytrees.

    Used to commit-or-skip an optimizer update on overflow: functional state
    makes the reference's reversible-update machinery
    (`distributed_fused_adam.py:509-533`) unnecessary — we simply do not
    select the new state.

    A Python-bool ``pred`` (statically known, e.g. no loss scaler in the
    policy) short-circuits to the chosen tree with zero compiled ops.
    """
    if isinstance(pred, bool):
        return on_true if pred else on_false
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), on_true, on_false)


def tree_zeros_like(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def tree_size(tree):
    """Total element count over all leaves."""
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def global_norm(tree, ord=2):
    """Global L2 (or Linf) norm over all leaves, computed in fp32.

    Functional counterpart of ``amp_C.multi_tensor_l2norm``
    (`csrc/multi_tensor_l2norm_kernel.cu`); the per-arena Pallas version lives
    in ``apex_tpu.ops.multi_tensor``.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    if ord == 2:
        sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
        return jnp.sqrt(sq)
    elif ord == jnp.inf or ord == "inf":
        return jnp.stack(
            [jnp.max(jnp.abs(x.astype(jnp.float32))) for x in leaves]).max()
    raise ValueError(f"unsupported ord={ord}")
