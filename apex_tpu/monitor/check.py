"""Compile-level validation of the zero-dispatch telemetry contract.

The whole point of the in-graph :class:`~apex_tpu.monitor.Metrics` design
is that monitoring must not change the step's dispatch structure: the
counters ride along as extra outputs of the one compiled program, and no
host transfer happens until the logger flushes. These helpers let tests
(and ``python -m apex_tpu.ops``, see the ``monitor/no-extra-dispatch``
case) assert exactly that from the compiled HLO.
"""

from __future__ import annotations

from typing import List, Tuple

from apex_tpu.prof import hlo as _hlo

__all__ = ["HOST_TRAFFIC_MARKERS", "module_count_and_host_ops"]

# HLO spellings of device→host traffic inside a compiled module: outfeed/
# infeed pairs, raw send/recv, and the python-callback custom-call targets
HOST_TRAFFIC_MARKERS = (
    " outfeed(", " infeed(", " send(", " send-done(", " recv(",
    " recv-done(", "xla_python_cpu_callback", "xla_python_gpu_callback",
    "tpu_host_callback", "HostCompute",
)


def module_count_and_host_ops(fn, *args, **kwargs) -> Tuple[int, List[str]]:
    """(number of HLO modules, host-traffic instructions) of a compiled fn.

    A monitored train step must report the same module count as its
    unmonitored twin (one executable — no telemetry side-programs) and an
    empty host-traffic list (no per-step device→host syncs).
    """
    text = _hlo.compiled_hlo(fn, *args, **kwargs)
    n_modules = text.count("HloModule ") or 1
    host = [line.strip()[:160] for line in text.splitlines()
            if any(m in line for m in HOST_TRAFFIC_MARKERS)]
    return n_modules, host
