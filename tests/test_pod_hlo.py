"""Pod-scale collective-structure assertions (VERDICT r4 item 5).

The driver cannot attach 64 chips, but the collective structure of the
compiled step is a compile-time artifact: these tests compile the
O2+DDP flagship step and the ZeRO optimizer path and assert the
optimized HLO contains the intended collectives — one fused grad
all-reduce per step at full message size (or the reduce-scatter /
all-gather pair for ZeRO), never a per-tensor collective storm. The
same audit runs against a real v5e-64 topology via the AOT compiler
when the environment provides one (scripts/pod_comm_budget.py); here
the 8-device CPU mesh keeps it CI-runnable. Reference analogue: the
bucketed hierarchy apex hand-builds
(`apex/parallel/distributed.py:604-624`,
`apex/contrib/optimizers/distributed_fused_adam.py:250-290`).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from scripts.pod_comm_budget import collectives, lower_flagship


def _compile_resnet_step(mesh, n, delay_allreduce):
    # small ResNet keeps CI fast; the collective structure is the same,
    # and the step construction is the SAME code the v5e-64 evidence
    # compiles (scripts/pod_comm_budget.py)
    from apex_tpu import models

    model_small = models.ResNet(stage_sizes=[1, 1], num_classes=10,
                                width=16, dtype=jnp.bfloat16)
    lowered, params_s = lower_flagship(
        mesh, n, delay_allreduce=delay_allreduce, model=model_small,
        image_size=32, per_chip_batch=4)
    hlo = lowered.compile().as_text()
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params_s))
    n_tensors = len(jax.tree_util.tree_leaves(params_s))
    return hlo, n_params, n_tensors


def _xla_combines_allreduces(mesh) -> bool:
    """Feature-probe the backend's all-reduce combiner pass: two
    independent psums merge into one variadic all-reduce where the pass
    runs (older XLA CPU pipelines don't schedule it at all)."""
    def f(a, b):
        return jax.lax.psum(a, "data"), jax.lax.psum(b, "data")

    mapped = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))
    x = jnp.ones((8, 256), jnp.float32)
    hlo = mapped.lower(x, x).compile().as_text()
    n_ar = len([c for c in collectives(hlo) if c[0] == "all-reduce"])
    return n_ar <= 1


@pytest.mark.parametrize("delay", [True, False])
def test_ddp_one_fused_grad_allreduce(mesh8, delay):
    """The grad sync must compile to ~one full-size all-reduce — with
    delay_allreduce a flat per-dtype buffer, without it the XLA
    combiner's variadic merge — never one collective per tensor."""
    if not delay and not _xla_combines_allreduces(mesh8):
        pytest.skip("this XLA pipeline has no all-reduce combiner pass; "
                    "the fused-sync claim needs delay_allreduce here")
    hlo, n_params, n_tensors = _compile_resnet_step(mesh8, 8, delay)
    colls = collectives(hlo)
    # everything except the scalar loss pmean is grad traffic
    ars = [c for c in colls if c[0] == "all-reduce" and c[3] > 128]
    grad_bytes = n_params * 4
    assert n_tensors > 20, "model too small to prove no-storm"
    assert len(ars) <= 4, (
        f"collective storm: {len(ars)} all-reduces for "
        f"{n_tensors} tensors:\n" + "\n".join(map(str, ars)))
    total = sum(c[3] for c in ars)
    # XLA may algebraically move a stray small tensor's reduction out
    # of the fused op (CPU backend: 764 of 131176 bytes); the claim is
    # structural — bulk coverage, not bitwise byte accounting
    assert total >= int(grad_bytes * 0.95), (
        f"grad all-reduces cover {total} bytes < fp32 grads "
        f"{grad_bytes}")


def test_zero_optimizer_scatter_gather(mesh8):
    """DistributedFusedAdam (ZeRO): grads reduce-scatter to shards,
    updated params all-gather back — and no full-size all-reduce."""
    from apex_tpu import parallel
    from apex_tpu.optim import DistributedFusedAdam

    opt = DistributedFusedAdam(lr=1e-3, axis_name=parallel.DATA_AXIS)
    n_params = 1 << 20
    params = {"w": jax.ShapeDtypeStruct((n_params,), jnp.float32)}

    def step(params, xb):
        def loss_fn(p):
            return jnp.sum(jnp.square(p["w"])) * jnp.mean(xb)
        # grads stay UNREDUCED: the ZeRO optimizer's own pipeline does
        # psum_scatter -> shard update -> all_gather
        grads = jax.grad(loss_fn)(params)
        opt_state = opt.init(params)
        new_params, _ = opt.step(grads, opt_state, params)
        return new_params

    x_s = jax.ShapeDtypeStruct((8,), jnp.float32)
    stepped = jax.jit(jax.shard_map(
        step, mesh=mesh8,
        in_specs=(P(), P(parallel.DATA_AXIS)),
        out_specs=P(), check_vma=False))
    hlo = stepped.lower(params, x_s).compile().as_text()
    colls = collectives(hlo)
    kinds = {c[0] for c in colls}
    assert "reduce-scatter" in kinds, f"no reduce-scatter: {colls}"
    assert "all-gather" in kinds, f"no all-gather: {colls}"
    param_bytes = n_params * 4
    big_ar = [c for c in colls
              if c[0] == "all-reduce" and c[3] >= param_bytes // 2]
    assert not big_ar, (
        f"ZeRO path still moves full-size all-reduces: {big_ar}")


@pytest.mark.slow
def test_v5e64_aot_collective_structure():
    """The same audit against a REAL v5e-64 topology via the AOT
    compiler — the full-scale evidence. Skipped when the environment
    cannot AOT-compile for TPU topologies (CPU-only CI). ``slow``: the
    64-device AOT compile alone runs past the whole tier-1 budget's
    margin on CPU CI (290s+); the 8-device mesh audits above keep the
    structure pinned in-budget."""
    try:
        from jax.experimental import topologies
        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:8x8")
    except Exception as e:
        pytest.skip(f"no TPU AOT topology support: {e}")
    from jax.sharding import Mesh
    from apex_tpu import parallel
    mesh = Mesh(np.array(topo.devices), (parallel.DATA_AXIS,))
    try:
        hlo, n_params, n_tensors = _compile_resnet_step(mesh, 64, True)
    except Exception as e:
        pytest.skip(f"TPU AOT compile unavailable: {e}")
    colls = collectives(hlo)
    grad_bytes = n_params * 4
    ars = [c for c in colls if c[0] == "all-reduce" and c[3] > 128]
    assert len(ars) <= 4, ars
    # same 0.95 slack as the CPU sibling: XLA may algebraically move a
    # stray small tensor's reduction out of the fused op
    assert sum(c[3] for c in ars) >= int(grad_bytes * 0.95), ars
    # all 64 chips participate in one replica group — enumerated or
    # iota-printed form depending on XLA version
    import re as _re
    assert _re.search(r"replica_groups=(\{\{0,1,2,3|\[1,64\]<=\[64\])",
                      hlo), "no 64-wide replica group found"
