"""Pod-scale collective-structure assertions (VERDICT r4 item 5).

The driver cannot attach 64 chips, but the collective structure of the
compiled step is a compile-time artifact: these tests compile the
O2+DDP flagship step and the ZeRO optimizer path and assert the
optimized HLO contains the intended collectives — one fused grad
all-reduce per step at full message size (or the reduce-scatter /
all-gather pair for ZeRO), never a per-tensor collective storm. The
same audit runs against a real v5e-64 topology via the AOT compiler
when the environment provides one (scripts/pod_comm_budget.py); here
the 8-device CPU mesh keeps it CI-runnable. Reference analogue: the
bucketed hierarchy apex hand-builds
(`apex/parallel/distributed.py:604-624`,
`apex/contrib/optimizers/distributed_fused_adam.py:250-290`).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from scripts.pod_comm_budget import (collectives,
                                     hierarchical_structure_audit,
                                     lower_flagship, overlap_audit,
                                     stablehlo_collectives)


def _compile_resnet_step(mesh, n, delay_allreduce, **mode_kw):
    # small ResNet keeps CI fast; the collective structure is the same,
    # and the step construction is the SAME code the v5e-64 evidence
    # compiles (scripts/pod_comm_budget.py)
    from apex_tpu import models

    model_small = models.ResNet(stage_sizes=[1, 1], num_classes=10,
                                width=16, dtype=jnp.bfloat16)
    lowered, params_s = lower_flagship(
        mesh, n, delay_allreduce=delay_allreduce, model=model_small,
        image_size=32, per_chip_batch=4, **mode_kw)
    hlo = lowered.compile().as_text()
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params_s))
    n_tensors = len(jax.tree_util.tree_leaves(params_s))
    return hlo, n_params, n_tensors, lowered, params_s


_BUCKET_MSG = 30_000    # elements: splits the small model into 2 buckets


def _xla_combines_allreduces(mesh) -> bool:
    """Feature-probe the backend's all-reduce combiner pass: two
    independent psums merge into one variadic all-reduce where the pass
    runs (older XLA CPU pipelines don't schedule it at all)."""
    def f(a, b):
        return jax.lax.psum(a, "data"), jax.lax.psum(b, "data")

    mapped = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))
    x = jnp.ones((8, 256), jnp.float32)
    hlo = mapped.lower(x, x).compile().as_text()
    n_ar = len([c for c in collectives(hlo) if c[0] == "all-reduce"])
    return n_ar <= 1


@pytest.mark.parametrize("delay", [True, False])
def test_ddp_one_fused_grad_allreduce(mesh8, delay):
    """The grad sync must compile to ~one full-size all-reduce — with
    delay_allreduce a flat per-dtype buffer, without it the XLA
    combiner's variadic merge — never one collective per tensor."""
    if not delay and not _xla_combines_allreduces(mesh8):
        pytest.skip("this XLA pipeline has no all-reduce combiner pass; "
                    "the fused-sync claim needs delay_allreduce here")
    hlo, n_params, n_tensors, _, _ = _compile_resnet_step(mesh8, 8, delay)
    colls = collectives(hlo)
    # everything except the scalar loss pmean is grad traffic
    ars = [c for c in colls if c[0] == "all-reduce" and c[3] > 128]
    grad_bytes = n_params * 4
    assert n_tensors > 20, "model too small to prove no-storm"
    assert len(ars) <= 4, (
        f"collective storm: {len(ars)} all-reduces for "
        f"{n_tensors} tensors:\n" + "\n".join(map(str, ars)))
    total = sum(c[3] for c in ars)
    # XLA may algebraically move a stray small tensor's reduction out
    # of the fused op (CPU backend: 764 of 131176 bytes); the claim is
    # structural — bulk coverage, not bitwise byte accounting
    assert total >= int(grad_bytes * 0.95), (
        f"grad all-reduces cover {total} bytes < fp32 grads "
        f"{grad_bytes}")


def test_zero_optimizer_scatter_gather(mesh8):
    """DistributedFusedAdam (ZeRO): grads reduce-scatter to shards,
    updated params all-gather back — and no full-size all-reduce."""
    from apex_tpu import parallel
    from apex_tpu.optim import DistributedFusedAdam

    opt = DistributedFusedAdam(lr=1e-3, axis_name=parallel.DATA_AXIS)
    n_params = 1 << 20
    params = {"w": jax.ShapeDtypeStruct((n_params,), jnp.float32)}

    def step(params, xb):
        def loss_fn(p):
            return jnp.sum(jnp.square(p["w"])) * jnp.mean(xb)
        # grads stay UNREDUCED: the ZeRO optimizer's own pipeline does
        # psum_scatter -> shard update -> all_gather
        grads = jax.grad(loss_fn)(params)
        opt_state = opt.init(params)
        new_params, _ = opt.step(grads, opt_state, params)
        return new_params

    x_s = jax.ShapeDtypeStruct((8,), jnp.float32)
    stepped = jax.jit(jax.shard_map(
        step, mesh=mesh8,
        in_specs=(P(), P(parallel.DATA_AXIS)),
        out_specs=P(), check_vma=False))
    hlo = stepped.lower(params, x_s).compile().as_text()
    colls = collectives(hlo)
    kinds = {c[0] for c in colls}
    assert "reduce-scatter" in kinds, f"no reduce-scatter: {colls}"
    assert "all-gather" in kinds, f"no all-gather: {colls}"
    param_bytes = n_params * 4
    big_ar = [c for c in colls
              if c[0] == "all-reduce" and c[3] >= param_bytes // 2]
    assert not big_ar, (
        f"ZeRO path still moves full-size all-reduces: {big_ar}")


class TestBucketedOverlap:
    """Overlap-audit assertions for the bucketed backward-ordered sync
    (apex ``allreduce_bucket`` parity) on the CI mesh. The async
    start/done-pair half of the audit needs a TPU-scheduled module and
    lives in the slow v5e-64 test below + the ``ddp/overlap-start-done``
    compile-check case; here the structure (per-bucket all-reduces that
    the combiner cannot re-merge, wire dtype/bytes) is pinned."""

    def test_per_bucket_allreduces_not_merged(self, mesh8):
        from apex_tpu.parallel import comm

        hlo, n_params, _, _, params_s = _compile_resnet_step(
            mesh8, 8, False, bucket_allreduce=True,
            message_size=_BUCKET_MSG)
        plan = comm.bucket_plan(jax.tree_util.tree_leaves(params_s),
                                _BUCKET_MSG)
        assert len(plan) >= 2, "model too small to exercise bucketing"
        ars = [c for c in collectives(hlo)
               if c[0] == "all-reduce" and c[3] > 128]
        assert len(ars) >= len(plan), (
            f"buckets merged into {len(ars)} all-reduces "
            f"(plan has {len(plan)}):\n" + "\n".join(map(str, ars)))
        # no single terminal all-reduce carries the whole gradient
        grad_bytes = n_params * 4
        assert all(c[3] < grad_bytes for c in ars), ars
        # ...but together they still cover it
        assert sum(c[3] for c in ars) >= int(grad_bytes * 0.95)

    def test_bucket_bytes_bounded_by_message_size(self, mesh8):
        from apex_tpu.parallel import comm

        hlo, _, _, _, params_s = _compile_resnet_step(
            mesh8, 8, False, bucket_allreduce=True,
            message_size=_BUCKET_MSG)
        plan = comm.bucket_plan(jax.tree_util.tree_leaves(params_s),
                                _BUCKET_MSG)
        # bucketing is at tensor granularity: a single oversized tensor
        # may exceed the cap, exactly like the reference's
        # allreduce_bucket — the bound is max(cap, biggest tensor)
        biggest = max(int(np.prod(l.shape)) for l in
                      jax.tree_util.tree_leaves(params_s))
        cap_bytes = max(_BUCKET_MSG, biggest) * 4
        ars = [c for c in collectives(hlo)
               if c[0] == "all-reduce" and c[3] > 128]
        assert max(c[3] for c in ars) <= cap_bytes * 1.05, (ars,
                                                            cap_bytes)
        assert max(b.bytes() for b in plan) <= cap_bytes

    def test_bf16_wire_bytes_halved(self, mesh8):
        """compress="bf16": wire bytes ≤ 50% of the logical fp32 grad
        bytes. Asserted on the LOWERED module's collectives — CPU's
        float-normalization pass promotes bf16 all-reduces to f32 in
        the optimized text (TPU keeps them native; the slow v5e-64
        audit asserts the optimized module there)."""
        _, n_params, _, lowered, _ = _compile_resnet_step(
            mesh8, 8, False, bucket_allreduce=True,
            message_size=_BUCKET_MSG, compress="bf16")
        colls = stablehlo_collectives(lowered.as_text())
        ars = [c for c in colls if c[0] == "all-reduce" and c[3] > 128]
        assert ars and all(c[1] == "bf16" for c in ars), colls
        logical = n_params * 4
        wire = sum(c[3] for c in ars)
        assert wire <= logical * 0.505, (wire, logical)
        assert wire >= logical * 0.45, (wire, logical)

    def test_default_mode_structurally_unchanged(self, mesh8):
        """The default (no-bucket, no-compress) DDP path must compile
        to the same program as before this layer existed — same opcode
        sequence, same collectives (the compile-check case
        ``ddp/no-compress-bitident``, run here so CI owns it)."""
        from apex_tpu.ops import compile_check as cc

        fn = dict(cc.CASES)["ddp/no-compress-bitident"]
        fn()

    def test_overlap_audit_parses_async_pairs(self):
        """overlap_audit on a synthetic scheduled module: start/done
        pairs found, compute between them counted."""
        hlo = "\n".join([
            "%ars.1 = (f32[100]{0}, f32[100]{0}) "
            "all-reduce-start(%p0), replica_groups={{0,1}}",
            "%fusion.7 = f32[8]{0} fusion(%p1), kind=kLoop",
            "%dot.3 = f32[8,8]{1,0} dot(%p1, %p2)",
            "%ard.1 = f32[100]{0} all-reduce-done(%ars.1)",
            "%ars.2 = (f32[50]{0}, f32[50]{0}) "
            "all-reduce-start(%fusion.7), replica_groups={{0,1}}",
            "%ard.2 = f32[50]{0} all-reduce-done(%ars.2)",
        ])
        pairs = overlap_audit(hlo)
        assert len(pairs) == 2
        assert pairs[0]["compute_between"] == 2
        assert pairs[0]["bytes"] == 400
        assert pairs[1]["compute_between"] == 0


class TestHierarchicalSchedule:
    """The collectives-v2 structure pins on the CI mesh: the
    hierarchical comm_plan compiles to within-slice ICI hops plus
    one-member-per-slice DCN hops (APX203 absent), the per-hop dtype
    split is readable from the compiled module, and the committed
    NEGATIVE twin proves APX203 still fires on the flat path — the
    done-state of ROADMAP item 2 as standing static artifacts."""

    def _hier_compile(self, mesh2x4, dtypes=None):
        from apex_tpu import models
        from apex_tpu.lint.mesh_model import parse_mesh_spec
        from apex_tpu.parallel import hierarchy

        mm = parse_mesh_spec("dp2x4")
        kw = {} if dtypes is None else {"dtypes": dtypes}
        plan = hierarchy.plan_comm(mm, grad_bytes=1 << 20, **kw)
        model = models.ResNet(stage_sizes=[1, 1], num_classes=10,
                              width=16, dtype=jnp.bfloat16)
        lowered, params_s = lower_flagship(
            mesh2x4, 8, delay_allreduce=False, model=model,
            image_size=32, per_chip_batch=4,
            message_size=_BUCKET_MSG, comm_plan=plan)
        return lowered.compile().as_text(), mm, plan, params_s

    def test_one_member_per_slice_dcn_groups(self, mesh2x4):
        hlo, mm, plan, _ = self._hier_compile(mesh2x4)
        assert plan.dtype_by_link() == {"ici": "int8", "dcn": "int8"}
        dcn_i, ici_i = hierarchical_structure_audit(hlo, mm)
        assert dcn_i and ici_i

    def test_per_hop_dtype_split_in_wire_report(self, mesh2x4):
        from apex_tpu import monitor

        hlo, _, _, _ = self._hier_compile(mesh2x4)
        by_hop = monitor.wire_report(hlo_text=hlo)["by_hop"]
        assert "s8" in by_hop["ici"], by_hop
        assert "s8" in by_hop["dcn"], by_hop
        # the slice-local hops carry ~intra x the DCN shard traffic
        assert sum(by_hop["ici"].values()) > \
            sum(by_hop["dcn"].values()), by_hop

    def test_apx203_negative_twin_flat_path_still_fires(self, mesh8):
        """The gate's gate: the FLAT bucketed sync over the same
        2-slice model must still produce APX203 — otherwise the
        'hierarchical flagship is APX203-clean' claim passes
        vacuously."""
        from apex_tpu import models
        from apex_tpu.lint.mesh_model import parse_mesh_spec
        from apex_tpu.lint.spmd_pass import dcn_flat_findings

        model = models.ResNet(stage_sizes=[1, 1], num_classes=10,
                              width=16, dtype=jnp.bfloat16)
        lowered, _ = lower_flagship(
            mesh8, 8, delay_allreduce=False, model=model,
            image_size=32, per_chip_batch=4, bucket_allreduce=True,
            message_size=_BUCKET_MSG)
        findings = dcn_flat_findings(lowered.compile().as_text(),
                                     parse_mesh_spec("dp2x4"))
        assert findings, "flat DDP sync no longer trips APX203"
        assert all(f.rule == "dcn-flat-collective" for f in findings)

    def test_ef_residual_roundtrips_through_flagship_shapes(self,
                                                            mesh2x4):
        """Lowering with residual threading intact: comm_plan syncs
        inside the flagship compile without touching the default path
        (the bitident compile-check owns the None case)."""
        hlo, mm, plan, params_s = self._hier_compile(mesh2x4)
        # grad traffic present at full coverage: every f32 param
        # element crossed the ICI scatter as int8 payload
        from apex_tpu import monitor
        by_hop = monitor.wire_report(hlo_text=hlo)["by_hop"]
        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(params_s))
        assert by_hop["ici"].get("s8", 0) >= n_params


@pytest.mark.slow
def test_v5e256_2slice_aot_hierarchical_audit():
    """CI pin of the pod-scale evidence: the hierarchical comm_plan
    compiled AOT for a 256-chip v5e target factored as 2 (modeled)
    slices x 128 chips — one-member-per-slice DCN reduce groups and
    the per-hop dtype split asserted from the real TPU-scheduled HLO
    (int8 payloads survive TPU optimization; CPU promotes only float
    wires). Skipped where the TPU AOT compiler is unavailable, exactly
    like the v5e-64 siblings — the 8-device structural twins above
    keep the shape pinned in-budget."""
    try:
        from jax.experimental import topologies
        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:16x16")
    except Exception as e:
        pytest.skip(f"no TPU AOT topology support: {e}")
    from jax.sharding import Mesh

    from apex_tpu import models, monitor
    from apex_tpu.lint.mesh_model import parse_mesh_spec
    from apex_tpu.parallel import hierarchy

    n = len(topo.devices)
    assert n == 256
    mesh = Mesh(np.array(topo.devices).reshape(2, n // 2),
                ("data_inter", "data_intra"))
    mm = parse_mesh_spec(f"dp2x{n // 2}")
    model = models.ResNet(stage_sizes=[1, 1], num_classes=10,
                          width=16, dtype=jnp.bfloat16)
    plan = hierarchy.plan_comm(mm, grad_bytes=1 << 20)
    try:
        lowered, _ = lower_flagship(
            mesh, n, delay_allreduce=False, model=model, image_size=32,
            per_chip_batch=4, message_size=_BUCKET_MSG, comm_plan=plan)
        hlo = lowered.compile().as_text()
    except Exception as e:
        pytest.skip(f"TPU AOT compile unavailable: {e}")
    dcn_i, ici_i = hierarchical_structure_audit(hlo, mm)
    assert dcn_i and ici_i
    by_hop = monitor.wire_report(hlo_text=hlo)["by_hop"]
    assert "s8" in by_hop.get("ici", {}), by_hop
    assert "s8" in by_hop.get("dcn", {}), by_hop


@pytest.mark.slow
def test_v5e64_aot_overlap_and_compression():
    """The acceptance audit against a REAL v5e-64 topology: bucketed
    mode compiles to per-bucket all-reduces (no single terminal
    all-reduce — the structure the latency-hiding scheduler needs to
    emit start/done pairs behind backward; pairs themselves are
    asserted only when the printed module carries them, see below), and
    ``compress="bf16"`` moves ≤ 50% of the logical grad bytes in the
    OPTIMIZED module (bf16 is native on TPU). Skipped where the
    environment cannot AOT-compile for TPU topologies (CPU-only CI —
    the structural halves above keep it pinned in-budget)."""
    from apex_tpu.parallel import comm

    try:
        from jax.experimental import topologies
        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:8x8")
    except Exception as e:
        pytest.skip(f"no TPU AOT topology support: {e}")
    from jax.sharding import Mesh
    from apex_tpu import parallel
    mesh = Mesh(np.array(topo.devices), (parallel.DATA_AXIS,))

    try:
        hlo, n_params, _, _, params_s = _compile_resnet_step(
            mesh, 64, False, bucket_allreduce=True,
            message_size=_BUCKET_MSG)
    except Exception as e:
        pytest.skip(f"TPU AOT compile unavailable: {e}")
    plan = comm.bucket_plan(jax.tree_util.tree_leaves(params_s),
                            _BUCKET_MSG)
    grad_bytes = n_params * 4
    ars = [c for c in collectives(hlo)
           if c[0] == "all-reduce" and c[3] > 128]
    assert len(ars) >= len(plan) >= 2, (len(ars), len(plan))
    assert all(c[3] < grad_bytes for c in ars), "terminal all-reduce"
    # async start/done pairs appear only in modules printed AFTER the
    # latency-hiding scheduler's async conversion; the v5e AOT path
    # prints the optimized-but-sync form (measured: zero -start ops),
    # so the pair half is conditional — the per-bucket structure above
    # is what gives the scheduler its overlap freedom either way
    pairs = [p for p in overlap_audit(hlo) if p["bytes"] > 128]
    if pairs:
        assert any(p["compute_between"] > 0 for p in pairs), pairs

    hlo_bf16, n_params, _, _, _ = _compile_resnet_step(
        mesh, 64, False, bucket_allreduce=True,
        message_size=_BUCKET_MSG, compress="bf16")
    # scheduled TPU modules carry collectives as start/done pairs (the
    # audit reports payload bytes once per pair); unscheduled fall back
    # to the sync-collective scan
    pairs_bf16 = [p for p in overlap_audit(hlo_bf16)
                  if p["op"] == "all-reduce" and p["bytes"] > 128]
    if pairs_bf16:
        wire = sum(p["bytes"] for p in pairs_bf16)
    else:
        wire = sum(c[3] for c in collectives(hlo_bf16)
                   if c[0] == "all-reduce" and c[3] > 128)
    assert wire <= n_params * 4 * 0.505, (wire, n_params * 4)


@pytest.mark.slow
def test_v5e64_aot_collective_structure():
    """The same audit against a REAL v5e-64 topology via the AOT
    compiler — the full-scale evidence. Skipped when the environment
    cannot AOT-compile for TPU topologies (CPU-only CI). ``slow``: the
    64-device AOT compile alone runs past the whole tier-1 budget's
    margin on CPU CI (290s+); the 8-device mesh audits above keep the
    structure pinned in-budget."""
    try:
        from jax.experimental import topologies
        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:8x8")
    except Exception as e:
        pytest.skip(f"no TPU AOT topology support: {e}")
    from jax.sharding import Mesh
    from apex_tpu import parallel
    mesh = Mesh(np.array(topo.devices), (parallel.DATA_AXIS,))
    try:
        hlo, n_params, n_tensors = _compile_resnet_step(mesh, 64, True)
    except Exception as e:
        pytest.skip(f"TPU AOT compile unavailable: {e}")
    colls = collectives(hlo)
    grad_bytes = n_params * 4
    ars = [c for c in colls if c[0] == "all-reduce" and c[3] > 128]
    assert len(ars) <= 4, ars
    # same 0.95 slack as the CPU sibling: XLA may algebraically move a
    # stray small tensor's reduction out of the fused op
    assert sum(c[3] for c in ars) >= int(grad_bytes * 0.95), ars
    # all 64 chips participate in one replica group — enumerated or
    # iota-printed form depending on XLA version
    import re as _re
    assert _re.search(r"replica_groups=(\{\{0,1,2,3|\[1,64\]<=\[64\])",
                      hlo), "no 64-wide replica group found"
