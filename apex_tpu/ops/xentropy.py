"""Fused label-smoothing softmax cross-entropy.

TPU-native rebuild of `xentropy_cuda`
(`apex/contrib/csrc/xentropy/xentropy_kernel.cu:1-722`,
`apex/contrib/xentropy/softmax_xentropy.py:4-28`): one forward pass
computes per-row losses with in-kernel label smoothing, saving only the
log-sum-exp residual (the reference's ``max_log_sum_exp`` memory win — the
softmax output is never materialized); the backward kernel recomputes the
softmax from logits + lse in registers.

loss_i = lse_i − (1−ε)·x_i[y_i] − (ε/K)·Σ_j x_ij
dx_ij = g_i · (exp(x_ij − lse_i) − (1−ε)·1[j=y_i] − ε/K)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import use_interpret

LANES = 128


def _row_block(v_padded: int, n_bufs: int, itemsize: int = 4) -> int:
    """Rows per grid step: size the vocab-wide blocks to a ~6 MiB
    double-buffered budget over ``n_bufs`` logits-sized buffers of the
    actual ``itemsize`` (bf16 logits take 2-3x larger rows than the old
    fp32-assuming 1 MiB bound — per-step overhead amortizes over fewer,
    fatter steps; measured on the BERT-vocab shapes)."""
    r = (8 << 20) // (2 * n_bufs * itemsize * v_padded)
    return max(16, min(256, (r // 16) * 16))


def _pad2(x2, rows, cols):
    n, c = x2.shape
    if n == rows and c == cols:
        return x2
    return jnp.pad(x2, ((0, rows - n), (0, cols - c)))


def _fwd_kernel(v, smoothing, x_ref, lab_ref, loss_ref, lse_ref):
    x = x_ref[:].astype(jnp.float32)
    r, vp = x.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (r, vp), 1)
    # the lane block covers the vocab dim exactly (vp == v in
    # _fwd_call/_bwd_call), so the vocab-validity mask is statically
    # all-true and its where passes are elided — each is a full
    # (r, 30522)-class VPU sweep at BERT shapes
    padded = vp > v
    if padded:
        mask = cols < v
        x = jnp.where(mask, x, -jnp.inf)
    xmax = jnp.max(x, axis=1, keepdims=True)
    # padded lanes already hold -inf in x, so exp underflows to 0
    lse = xmax + jnp.log(jnp.sum(jnp.exp(x - xmax), axis=1,
                                 keepdims=True))
    labels = lab_ref[:, :1]                      # (r, 1) int32
    onehot = cols == labels
    x_label = jnp.sum(jnp.where(onehot, x, 0.0), axis=1, keepdims=True)
    loss = lse - (1.0 - smoothing) * x_label
    if smoothing:
        xs = jnp.where(mask, x, 0.0) if padded else x
        loss = loss - (smoothing / v) * jnp.sum(
            xs, axis=1, keepdims=True)
    # ignored rows (label < 0) produce zero loss (padding convention)
    valid = labels >= 0
    loss_ref[:] = jnp.where(valid, loss, 0.0) + jnp.zeros((r, LANES),
                                                          jnp.float32)
    lse_ref[:] = lse + jnp.zeros((r, LANES), jnp.float32)


def _bwd_kernel(v, smoothing, x_ref, lab_ref, lse_ref, g_ref, dx_ref):
    x = x_ref[:].astype(jnp.float32)
    r, vp = x.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (r, vp), 1)
    labels = lab_ref[:, :1]
    lse = lse_ref[:, :1]
    g = g_ref[:, :1]
    prob = jnp.exp(x - lse)
    target = (1.0 - smoothing) * (cols == labels)
    if smoothing:
        target = target + smoothing / v
    if vp > v:                   # vp == v by construction; see _fwd_call
        mask = cols < v
        prob = jnp.where(mask, prob, 0.0)
        if smoothing:
            target = jnp.where(mask, target, 0.0)
    dx = g * (prob - target)
    dx = jnp.where(labels >= 0, dx, 0.0)
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _broadcast_lanes(vec, npad):
    out = jnp.zeros((npad,), vec.dtype).at[:vec.shape[0]].set(vec)
    return jnp.broadcast_to(out[:, None], (npad, LANES))


def _fwd_call(x2, labels, smoothing, block_rows=None):
    n, v = x2.shape
    # lane dim = the full vocab dim (legal for Mosaic whatever v is) —
    # padding V up to a 128 multiple would copy the whole logits tensor
    # (500 MB at BERT vocab) just to round 30522 → 30592
    vp = v
    if block_rows is None:
        from apex_tpu.ops import autotune
        block_rows = autotune.tuned_rows("xentropy", (n, v), x2.dtype)
    r = (block_rows if block_rows is not None
         else _row_block(-(-v // LANES) * LANES, 1, x2.dtype.itemsize))
    npad = -(-n // r) * r
    xp = _pad2(x2, npad, vp)
    # padding rows get label -1 → zero loss
    lab = _broadcast_lanes(
        jnp.where(jnp.arange(npad) < n,
                  jnp.pad(labels.astype(jnp.int32), (0, npad - n)),
                  -1), npad)

    row = pl.BlockSpec((r, vp), lambda i: (i, 0), memory_space=pltpu.VMEM)
    lane = pl.BlockSpec((r, LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, v, smoothing),
        grid=(npad // r,),
        in_specs=[row, lane],
        out_specs=(lane, lane),
        out_shape=(jax.ShapeDtypeStruct((npad, LANES), jnp.float32),) * 2,
        interpret=use_interpret(),
    )(xp, lab)
    return loss[:n, 0], lse[:n, 0]


def _bwd_call(x2, labels, lse, g, smoothing, block_rows=None):
    n, v = x2.shape
    vp = v                      # full-dim lane blocks; see _fwd_call
    if block_rows is None:
        from apex_tpu.ops import autotune
        block_rows = autotune.tuned_rows("xentropy", (n, v), x2.dtype)
    r = (block_rows if block_rows is not None
         else _row_block(-(-v // LANES) * LANES, 2, x2.dtype.itemsize))
    npad = -(-n // r) * r
    xp = _pad2(x2, npad, vp)
    lab = _broadcast_lanes(
        jnp.where(jnp.arange(npad) < n,
                  jnp.pad(labels.astype(jnp.int32), (0, npad - n)),
                  -1), npad)
    lsep = _broadcast_lanes(lse, npad)
    gp = _broadcast_lanes(g.astype(jnp.float32), npad)

    row = pl.BlockSpec((r, vp), lambda i: (i, 0), memory_space=pltpu.VMEM)
    lane = pl.BlockSpec((r, LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, v, smoothing),
        grid=(npad // r,),
        in_specs=[row, lane, lane, lane],
        out_specs=row,
        out_shape=jax.ShapeDtypeStruct((npad, vp), x2.dtype),
        interpret=use_interpret(),
    )(xp, lab, lsep, gp)
    return dx[:n, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_cross_entropy_loss(logits, labels, smoothing=0.0):
    """Per-example losses, fused. ``logits`` (..., V), int ``labels``
    (...,); rows with negative labels contribute zero loss/grad. The
    callable mirror of ``SoftmaxCrossEntropyLoss.apply``
    (`apex/contrib/xentropy/softmax_xentropy.py:4-28`)."""
    shape = logits.shape[:-1]
    loss, _ = _fwd_call(logits.reshape(-1, logits.shape[-1]),
                        labels.reshape(-1), smoothing)
    return loss.reshape(shape)


def _sce_fwd(logits, labels, smoothing):
    x2 = logits.reshape(-1, logits.shape[-1])
    lab = labels.reshape(-1)
    loss, lse = _fwd_call(x2, lab, smoothing)
    return loss.reshape(labels.shape), (logits, labels, lse)


def _sce_bwd(smoothing, res, g):
    logits, labels, lse = res
    dx = _bwd_call(logits.reshape(-1, logits.shape[-1]),
                   labels.reshape(-1), lse, g.reshape(-1), smoothing)
    return dx.reshape(logits.shape), None


softmax_cross_entropy_loss.defvjp(_sce_fwd, _sce_bwd)


def softmax_cross_entropy_reference(logits, labels, smoothing=0.0):
    """Pure-jnp oracle for tests (`test_label_smoothing.py`'s local
    reference)."""
    x = logits.astype(jnp.float32)
    v = x.shape[-1]
    lse = jax.scipy.special.logsumexp(x, axis=-1)
    onehot = jax.nn.one_hot(jnp.maximum(labels, 0), v)
    x_label = jnp.sum(x * onehot, axis=-1)
    loss = lse - (1 - smoothing) * x_label - smoothing / v * jnp.sum(
        x, axis=-1)
    return jnp.where(labels >= 0, loss, 0.0)
