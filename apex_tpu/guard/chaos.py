"""Deterministic chaos injection: prove recovery, don't hope for it.

A resilience stack that has never seen a fault is a liability — the
chaos harness makes faults a *reproducible input*. A :class:`FaultPlan`
is a pure function of its construction (explicit faults, or
:meth:`FaultPlan.random` from a seed): keyed by ``(step, rank, site)``,
JSON round-trippable, and replayable bit-for-bit — the same plan run
twice injects the same faults at the same instants, which is what lets
``scripts/chaos_audit.py`` compare a faulted run against a fault-free
oracle bitwise.

Injection sites span the layers a real pod run fails at:

========== ============================ ================================
site       kinds                        mechanism
========== ============================ ================================
batch      nan, inf, corrupt, overflow  host: poison the input batch
grads      nan, inf                     in-graph (`inject_grads` + the
                                        per-step ``fault_code`` input)
activations nan                         in-graph (`inject_activation`)
params     nan, bitflip,                host: corrupt committed state
           bitflip_mantissa             AFTER the step (silent-DMA /
                                        bit-flip model);
                                        ``bitflip_mantissa`` flips a
                                        mantissa bit only (``arg``
                                        selects which, mod the dtype's
                                        mantissa width) so the
                                        corrupted value is guaranteed
                                        FINITE — silent to the
                                        nonfinite-param probe, the
                                        exact class the integrity
                                        fingerprints exist for
collective stall                        host: sleep — a peer wedged in a
                                        collective (watchdog territory)
proc       sigkill                      host: SIGKILL this process
ckpt       truncate                     host: truncate the newest
                                        committed checkpoint's data file
cluster    lease_expire, zombie_resume, host: control-plane faults
           split_brain                  against apex_tpu.cluster (needs
                                        ``post_step(membership=...)``)
========== ============================ ================================

The ``cluster`` site exercises the generation-fencing paths
(docs/resilience.md#control-plane): ``lease_expire`` backdates this
rank's lease so the cluster declares it dead while the process keeps
running (what a long VM pause looks like from outside);
``zombie_resume`` SIGSTOPs this process — the driver (``cluster_audit``
or a test) escalates + relaunches around the pause and SIGCONTs it
afterwards, turning it into a live zombie whose late writes the fence
must refuse; ``split_brain`` makes this rank *claim* a generation the
cluster never committed (``arg`` = the offset, default +1), which every
verifier (intent MACs + generation checks, commit fences) must refuse.

In-graph sites work through one extra i32 scalar step input (the
``fault_code``): the instrumented step calls
``grads = chaos.inject_grads(grads, code)`` and XLA folds the
``jnp.where`` selects in; a plan with no in-graph faults passes code 0
every step and the selects choose the clean branch. Chaos
instrumentation is for test/audit builds — production steps simply never
take the argument.
"""

from __future__ import annotations

import json
import os
import signal
import time
from typing import Dict, Iterable, NamedTuple, Optional, Tuple

import numpy as np

__all__ = ["Fault", "FaultPlan", "ChaosHarness",
           "inject_grads", "inject_activation",
           "C_GRAD_NAN", "C_GRAD_INF", "C_ACT_NAN", "SITES"]

#: fault_code bits for the in-graph sites
C_GRAD_NAN = 1
C_GRAD_INF = 2
C_ACT_NAN = 4

SITES: Dict[str, Tuple[str, ...]] = {
    "batch": ("nan", "inf", "corrupt", "overflow"),
    "grads": ("nan", "inf"),
    "activations": ("nan",),
    "params": ("nan", "bitflip", "bitflip_mantissa"),
    "collective": ("stall",),
    "proc": ("sigkill",),
    "ckpt": ("truncate",),
    "cluster": ("lease_expire", "zombie_resume", "split_brain"),
}


class Fault(NamedTuple):
    """One planned fault. ``arg`` is the site-specific magnitude:
    corrupt amplitude / overflow factor / stall seconds / bit index."""
    step: int
    site: str
    kind: str
    rank: int = 0
    arg: float = 0.0


class FaultPlan:
    """A replayable, (step, rank, site)-keyed fault schedule."""

    def __init__(self, faults: Iterable[Fault] = (), *, seed: int = 0):
        self.seed = int(seed)
        self._by_key: Dict[Tuple[int, int, str], Fault] = {}
        for f in faults:
            self.add(f.step, f.site, f.kind, rank=f.rank, arg=f.arg)

    def add(self, step: int, site: str, kind: str, *, rank: int = 0,
            arg: float = 0.0) -> "FaultPlan":
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} — one of "
                             f"{sorted(SITES)}")
        if kind not in SITES[site]:
            raise ValueError(f"site {site!r} supports kinds "
                             f"{SITES[site]}, got {kind!r}")
        key = (int(step), int(rank), site)
        if key in self._by_key:
            raise ValueError(f"duplicate fault at (step={step}, "
                             f"rank={rank}, site={site})")
        self._by_key[key] = Fault(int(step), site, kind, int(rank),
                                  float(arg))
        return self

    @classmethod
    def random(cls, seed: int, n_steps: int, *, rates: Dict[str, float],
               ranks: int = 1) -> "FaultPlan":
        """A deterministic random plan: per (step, rank), each named
        ``site:kind`` (e.g. ``{"grads:nan": 0.05}``) fires with its
        rate. Pure function of ``(seed, n_steps, rates, ranks)`` — two
        calls build identical plans. At most one rate key per SITE:
        the plan is keyed by (step, rank, site), so two kinds on one
        site would silently under-deliver whichever loses the
        collision — build multi-kind-per-site plans with explicit
        :meth:`add` calls at distinct steps instead."""
        rng = np.random.RandomState(int(seed))
        plan = cls(seed=seed)
        specs = []
        seen_sites: Dict[str, str] = {}
        for name, rate in sorted(rates.items()):
            site, sep, kind = name.partition(":")
            if not sep or site not in SITES or kind not in SITES[site]:
                raise ValueError(
                    f"unknown fault rate key {name!r} — use "
                    f"'site:kind' with site in {sorted(SITES)} and a "
                    f"kind that site supports (a typo here would make "
                    f"a chaos soak pass vacuously)")
            if site in seen_sites:
                raise ValueError(
                    f"rate keys {seen_sites[site]!r} and {name!r} "
                    f"share the site {site!r}: plans are keyed by "
                    f"(step, rank, site), so one of them would be "
                    f"silently dropped on every collision — use "
                    f"explicit add() calls for multi-kind sites")
            seen_sites[site] = name
            specs.append((name, site, kind, float(rate)))
        for step in range(int(n_steps)):
            for rank in range(int(ranks)):
                for name, site, kind, rate in specs:
                    if rng.rand() < rate:
                        key = (step, rank, site)
                        if key not in plan._by_key:
                            plan._by_key[key] = Fault(step, site, kind,
                                                      rank, 0.0)
        return plan

    def at(self, step: int, rank: int, site: str) -> Optional[Fault]:
        return self._by_key.get((int(step), int(rank), site))

    def faults(self):
        return sorted(self._by_key.values())

    def fault_code(self, step: int, rank: int = 0) -> int:
        """The i32 bitmask driving the in-graph sites at this step."""
        code = 0
        g = self.at(step, rank, "grads")
        if g is not None:
            code |= C_GRAD_NAN if g.kind == "nan" else C_GRAD_INF
        a = self.at(step, rank, "activations")
        if a is not None:
            code |= C_ACT_NAN
        return code

    # -- replayable artifact ---------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed,
                           "faults": [list(f) for f in self.faults()]})

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        d = json.loads(text)
        return cls((Fault(int(s), site, kind, int(r), float(a))
                    for s, site, kind, r, a in d["faults"]),
                   seed=d.get("seed", 0))

    def __eq__(self, other):
        return (isinstance(other, FaultPlan)
                and self._by_key == other._by_key)

    def __len__(self):
        return len(self._by_key)


# -- in-graph injection helpers ------------------------------------------------

def _poison_first(x, bad, val):
    """NaN/Inf element 0 of ``x`` when ``bad`` (a traced bool scalar)."""
    import jax.numpy as jnp
    flat = jnp.reshape(x, (-1,))
    flat = flat.at[0].set(jnp.where(bad, jnp.asarray(val, flat.dtype),
                                    flat[0]))
    return jnp.reshape(flat, jnp.shape(x))


def inject_grads(grads, code):
    """Poison element 0 of every float grad leaf with NaN (code bit
    ``C_GRAD_NAN``) or Inf (``C_GRAD_INF``). Identity when neither bit
    is set — the clean-path select XLA folds."""
    import jax
    import jax.numpy as jnp
    code = jnp.asarray(code, jnp.int32)
    bad_nan = (code & C_GRAD_NAN) != 0
    bad_inf = (code & C_GRAD_INF) != 0
    bad = jnp.logical_or(bad_nan, bad_inf)
    val = jnp.where(bad_nan, jnp.float32(jnp.nan), jnp.float32(jnp.inf))

    def _one(g):
        if not jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            return g
        return _poison_first(g, bad, val)

    return jax.tree_util.tree_map(_one, grads)


def inject_activation(x, code):
    """Poison element 0 of an activation with NaN when ``C_ACT_NAN``."""
    import jax.numpy as jnp
    code = jnp.asarray(code, jnp.int32)
    return _poison_first(x, (code & C_ACT_NAN) != 0, jnp.nan)


# -- the host driver -----------------------------------------------------------

class ChaosHarness:
    """Applies a :class:`FaultPlan` to a training loop's host seams.

    ::

        harness = chaos.ChaosHarness(plan)
        for step, (x, y) in enumerate(batches):
            x, y = harness.filter_batch(step, (x, y))
            code = harness.fault_code(step)
            state, gs, loss = jstep(state, gs, x, y, code)
            state = harness.post_step(step, state, ckpt_root=root)

    Host injections are a pure function of ``(plan, step, rank)`` —
    the corrupt-batch noise derives its RandomState from
    ``plan.seed ^ step``, never from consumed global RNG.
    """

    def __init__(self, plan: FaultPlan, *, rank: int = 0,
                 replica: Optional[int] = None):
        self.plan = plan
        self.rank = int(rank)
        #: dp-axis replica index whose device buffers a ``params``
        #: fault corrupts. ``None`` (legacy) corrupts the LOGICAL value
        #: — the device_put round-trip re-replicates the corruption to
        #: every replica identically, which can never diverge the dp
        #: axis. Set a replica index to model the real silent-SDC
        #: fault: one replica's buffer flips while the sharding still
        #: claims replication (the class
        #: :mod:`apex_tpu.guard.integrity` defends).
        self.replica = replica
        #: host log of injections performed: (step, site, kind)
        self.injected: list = []

    def _note(self, step, f: Fault):
        self.injected.append((int(step), f.site, f.kind))

    def fault_code(self, step: int) -> int:
        code = self.plan.fault_code(step, self.rank)
        for site in ("grads", "activations"):
            f = self.plan.at(step, self.rank, site)
            if f is not None:
                self._note(step, f)
        return code

    def filter_batch(self, step: int, batch):
        """Apply any ``batch``-site fault to an ``(x, y, ...)`` tuple of
        host numpy arrays; returns the (possibly poisoned) batch."""
        f = self.plan.at(step, self.rank, "batch")
        if f is None:
            return batch
        x = np.array(batch[0], copy=True)
        if f.kind == "nan":
            x.reshape(-1)[0] = np.nan
        elif f.kind == "inf":
            x.reshape(-1)[0] = np.inf
        elif f.kind == "corrupt":
            amp = f.arg or 1e4
            rng = np.random.RandomState((self.plan.seed ^ step)
                                        & 0x7FFFFFFF)
            x = rng.uniform(-amp, amp, x.shape).astype(x.dtype)
        elif f.kind == "overflow":
            x = x * np.asarray(f.arg or 1e30, x.dtype)
        self._note(step, f)
        return (x,) + tuple(batch[1:])

    def post_step(self, step: int, state, *, ckpt_root: Optional[str]
                  = None, membership=None):
        """Apply after-the-commit faults: param corruption, a stalled
        collective, SIGKILL, checkpoint truncation, cluster
        control-plane faults (``membership`` — an
        :class:`apex_tpu.cluster.ClusterMembership` — is required when
        the plan carries a ``cluster`` fault). Returns the (possibly
        corrupted) state tree."""
        f = self.plan.at(step, self.rank, "cluster")
        if f is not None:
            if membership is None:
                raise ValueError("cluster fault planned but post_step "
                                 "got no membership")
            self._note(step, f)
            if f.kind == "lease_expire":
                membership.lease.expire_now()
            elif f.kind == "split_brain":
                # claim (locally!) an epoch the cluster never committed
                # — downstream fences/intent verification must refuse
                membership.claim_generation(
                    membership.generation + (int(f.arg) or 1))
            else:                       # zombie_resume
                # pause self; the DRIVER escalates + relaunches around
                # the pause and SIGCONTs this process into a zombie
                os.kill(os.getpid(), signal.SIGSTOP)
        f = self.plan.at(step, self.rank, "params")
        if f is not None:
            state = self._corrupt_params(state, f, replica=self.replica)
            self._note(step, f)
        f = self.plan.at(step, self.rank, "collective")
        if f is not None:
            self._note(step, f)
            time.sleep(float(f.arg or 1.0))
        f = self.plan.at(step, self.rank, "ckpt")
        if f is not None:
            if ckpt_root is None:
                raise ValueError("ckpt fault planned but post_step got "
                                 "no ckpt_root")
            self._note(step, f)
            self.truncate_latest_checkpoint(ckpt_root)
        f = self.plan.at(step, self.rank, "proc")
        if f is not None:
            self._note(step, f)
            os.kill(os.getpid(), signal.SIGKILL)
        return state

    # -- host corruption mechanics --------------------------------------------

    @staticmethod
    def _mantissa_bits(dtype) -> Optional[int]:
        """The dtype's mantissa width (f32: 23, f16: 10, bf16: 7 —
        asked of np.finfo so bf16's narrow mantissa is never confused
        with f16's by item size). Bits 0..m-1 never touch the
        exponent, so a finite value STAYS finite."""
        try:
            return int(np.finfo(dtype).nmant)
        except Exception:
            return None

    @classmethod
    def _corrupt_params(cls, state, f: Fault, replica=None):
        """Poison element 0 of the FIRST float leaf (deterministic under
        a fixed tree structure): NaN, a real bit flip of the float32
        representation (``arg`` = bit index, default 30 — the top
        exponent bit, turning a weight into ~1e38), or a MANTISSA-only
        flip (``bitflip_mantissa``: ``arg`` selects the bit, taken mod
        the dtype's mantissa width, so the corrupted value is
        guaranteed finite — a high-bit flip can yield NaN/Inf and get
        caught by the loud nonfinite-param probe, which never
        exercises the silent path the integrity fingerprints defend).

        ``replica`` targets ONE dp replica's device buffers (the
        sharding still claims replication — the silent-divergence
        model); ``None`` corrupts the logical value on every replica
        identically."""
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(state)
        for i, leaf in enumerate(leaves):
            arr = np.array(np.asarray(leaf), copy=True)
            if not np.issubdtype(arr.dtype, np.floating) or arr.size == 0:
                continue
            flat = arr.reshape(-1)
            if f.kind == "nan":
                flat[0] = np.nan
            elif f.kind == "bitflip_mantissa":
                m = cls._mantissa_bits(arr.dtype)
                uint = {4: np.uint32, 2: np.uint16,
                        1: np.uint8}.get(arr.dtype.itemsize)
                if m is None or uint is None:   # f64 etc: scale the
                    flat[0] = flat[0] * (1.0 + 2.0 ** -12) \
                        if flat[0] != 0 else 2.0 ** -24  # mantissa
                else:
                    bit = int(f.arg) % m
                    iv = flat[:1].view(uint)
                    iv[0] ^= uint(1 << bit)
                assert np.isfinite(flat[0]), \
                    "mantissa flip produced a non-finite value"
            else:
                bit = int(f.arg) or 30
                if arr.dtype == np.float32:
                    iv = flat[:1].view(np.uint32)
                    iv[0] ^= np.uint32(1 << bit)
                else:
                    flat[0] = -flat[0] * 3.4e38
            new = arr.reshape(np.shape(leaf))
            leaves = list(leaves)
            if replica is not None and hasattr(leaf, "sharding"):
                leaves[i] = cls._poison_replica(leaf, new, replica)
            else:
                if hasattr(leaf, "sharding"):
                    new = jax.device_put(new, leaf.sharding)
                leaves[i] = new
            return jax.tree_util.tree_unflatten(treedef, leaves)
        return state

    @staticmethod
    def _poison_replica(leaf, corrupted, replica: int):
        """Rebuild a replicated array with ONE replica's buffer holding
        ``corrupted`` bits and every other replica keeping the original
        — the sharding is unchanged, so downstream code still believes
        the array is replicated (``np.asarray`` keeps reading replica
        0). The exact lie a silent DMA/bit-flip fault tells."""
        import jax
        if not leaf.sharding.is_fully_replicated:
            # on a multi-axis (dp x mp) mesh a flat device index is
            # NOT a dp replica id, and a sharded leaf's per-device
            # buffers are not full copies — refuse loudly rather than
            # corrupt the wrong shard with the wrong shape
            raise ValueError(
                "ChaosHarness(replica=...) corrupts one replica of a "
                "FULLY-REPLICATED leaf (replica = flat device index "
                "of an all-data-parallel mesh); this leaf's sharding "
                f"is {leaf.sharding} — target it via an explicit "
                "per-shard fault instead")
        mesh = leaf.sharding.mesh
        devices = list(mesh.devices.flat)
        if not 0 <= int(replica) < len(devices):
            raise ValueError(f"replica {replica} out of range for a "
                             f"{len(devices)}-device mesh")
        orig = np.array(np.asarray(leaf), copy=True)
        bufs = [jax.device_put(corrupted if i == int(replica) else orig,
                               d)
                for i, d in enumerate(devices)]
        return jax.make_array_from_single_device_arrays(
            leaf.shape, leaf.sharding, bufs)

    @staticmethod
    def truncate_latest_checkpoint(root: str) -> Optional[str]:
        """Truncate the newest committed checkpoint's largest data file
        to half — the manifest hash no longer matches, so a restore of
        this checkpoint must refuse (and a guard rewind falls back to
        the previous one). Returns the truncated path."""
        from apex_tpu.ckpt import format as _fmt
        d = _fmt.latest_checkpoint(root)
        if d is None:
            return None
        npz = [os.path.join(d, n) for n in os.listdir(d)
               if n.endswith(".npz")]
        if not npz:
            return None
        target = max(npz, key=os.path.getsize)
        size = os.path.getsize(target)
        with open(target, "r+b") as fh:
            fh.truncate(max(size // 2, 1))
        return target
