"""DCGAN generator/discriminator — the multi-model/multi-loss example.

The reference's DCGAN example is the canonical exercise of multi-model amp
(`examples/dcgan/main_amp.py:215-253`: ``amp.initialize([netD, netG],
[optD, optG], num_losses=3)`` with a ``loss_id`` per backward). These are
the same G/D architectures in NHWC flax, used by the multi-scaler tests
and the dcgan example.
"""

from __future__ import annotations

import jax.numpy as jnp
import flax.linen as nn


class Generator(nn.Module):
    """z (N, 1, 1, nz) → image (N, 64, 64, nc)."""
    nz: int = 100
    ngf: int = 64
    nc: int = 3

    @nn.compact
    def __call__(self, z, train: bool = True):
        def up(x, feats, first=False):
            x = nn.ConvTranspose(
                feats, (4, 4), (2, 2) if not first else (1, 1),
                padding="VALID" if first else "SAME", use_bias=False)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return nn.relu(x)

        x = up(z, self.ngf * 8, first=True)        # 4x4
        x = up(x, self.ngf * 4)                    # 8x8
        x = up(x, self.ngf * 2)                    # 16x16
        x = up(x, self.ngf)                        # 32x32
        x = nn.ConvTranspose(self.nc, (4, 4), (2, 2), use_bias=False)(x)
        return jnp.tanh(x)                         # 64x64


class Discriminator(nn.Module):
    """image (N, 64, 64, nc) → logit (N,)."""
    ndf: int = 64
    nc: int = 3

    @nn.compact
    def __call__(self, x, train: bool = True):
        def down(x, feats, bn=True):
            x = nn.Conv(feats, (4, 4), (2, 2), use_bias=False)(x)
            if bn:
                x = nn.BatchNorm(use_running_average=not train)(x)
            return nn.leaky_relu(x, 0.2)

        x = down(x, self.ndf, bn=False)            # 32x32
        x = down(x, self.ndf * 2)                  # 16x16
        x = down(x, self.ndf * 4)                  # 8x8
        x = down(x, self.ndf * 8)                  # 4x4
        x = nn.Conv(1, (4, 4), (1, 1), padding="VALID", use_bias=False)(x)
        return x.reshape(x.shape[0])
