"""apex_tpu.ops — fused Pallas kernels (SURVEY.md §2.10).

Every native module of the reference maps here: the `amp_C` multi-tensor
family (multi_tensor.py), the optimizer functors (optim_kernels.py), fused
LayerNorm / MLP / softmax-CE / NHWC BatchNorm / attention (their own
modules). All kernels run compiled on TPU and in interpret mode elsewhere.
"""

from apex_tpu.ops.multi_tensor import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_maxnorm,
    multi_tensor_scale,
    per_tensor_l2norm,
)
from apex_tpu.ops import optim_kernels
from apex_tpu.ops.layer_norm import (
    FusedLayerNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    layer_norm_reference,
)
from apex_tpu.ops.mlp import MLP, fused_mlp, mlp_reference
from apex_tpu.ops.xentropy import (
    softmax_cross_entropy_loss,
    softmax_cross_entropy_reference,
)
from apex_tpu.ops.group_bn import BatchNorm2d_NHWC, bn_group_spec
from apex_tpu.ops.bn_act import (
    FusedBNAct,
    bn_act_reference,
    bn_act_train,
    bn_add_act_train,
)
from apex_tpu.ops.conv_bn import (
    ConvBNAct,
    conv_bn_act_train,
    conv_bn_add_act_train,
)
from apex_tpu.ops.attention import (
    flash_attention,
    attention_reference,
    mask_softmax_dropout,
)
from apex_tpu.ops.multihead_attn import SelfMultiheadAttn, EncdecMultiheadAttn
from apex_tpu.ops import autotune

__all__ = [
    "autotune",
    "multi_tensor_axpby", "multi_tensor_l2norm", "multi_tensor_maxnorm",
    "multi_tensor_scale", "per_tensor_l2norm", "optim_kernels",
    "FusedLayerNorm", "fused_layer_norm", "fused_layer_norm_affine",
    "layer_norm_reference", "MLP", "fused_mlp", "mlp_reference",
    "softmax_cross_entropy_loss", "softmax_cross_entropy_reference",
    "BatchNorm2d_NHWC", "bn_group_spec",
    "FusedBNAct", "bn_act_reference", "bn_act_train", "bn_add_act_train",
    "ConvBNAct", "conv_bn_act_train", "conv_bn_add_act_train",
    "flash_attention", "attention_reference", "mask_softmax_dropout",
    "SelfMultiheadAttn", "EncdecMultiheadAttn",
]
