"""CLI: parse a profiler logdir into a per-op device-time table.

The command-line mirror of the reference's offline analyzers
(`python -m apex.pyprof.parse` over nvprof SQLite →
`apex/pyprof/parse/parse.py:1-30`, and the analyzed table of
`python -m apex.pyprof.prof` → `apex/pyprof/prof/prof.py:1-256`). Here
the artifact is a ``jax.profiler`` trace directory (written by
``apex_tpu.prof.trace`` or any jax trace capture) and the analysis is
per-HLO-op device timing plus category rollups.

Usage::

    python -m apex_tpu.prof /tmp/trace            # top-30 op table
    python -m apex_tpu.prof /tmp/trace --top 100
    python -m apex_tpu.prof /tmp/trace --csv      # machine-readable
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.prof",
        description="Per-op device-time analysis of a jax.profiler trace")
    p.add_argument("logdir", help="trace directory (contains *.xplane.pb)")
    p.add_argument("--top", type=int, default=30,
                   help="rows in the op table (default 30)")
    p.add_argument("--csv", action="store_true",
                   help="emit name,category,count,total_us rows")
    args = p.parse_args(argv)

    from apex_tpu.prof.xplane import parse_trace

    tp = parse_trace(args.logdir)
    if not tp.ops:
        print("no device ops found in trace (CPU-only run, or no "
              "*.xplane.pb under the logdir)", file=sys.stderr)
        return 1
    if args.csv:
        print("name,category,occurrences,total_us")
        for r in tp.ops:
            print(f"{r.name},{r.category},{r.occurrences},"
                  f"{r.total_us:.1f}")
    else:
        print(tp.table(top=args.top))
        print()
        for cat, us in sorted(tp.by_category().items(),
                              key=lambda kv: -kv[1]):
            print(f"{cat:<16} {us:12.0f}us")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
