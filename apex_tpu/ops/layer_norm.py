"""Fused LayerNorm — Pallas forward/backward with custom VJP.

TPU-native rebuild of `fused_layer_norm_cuda`
(`csrc/layer_norm_cuda.cpp:1-241`, `layer_norm_cuda_kernel.cu:280-807`):
one kernel normalizes a block of rows (statistics + normalize + affine in a
single VMEM pass, `cuApplyLayerNorm`), and the backward kernel produces
dgrad plus *partial* weight/bias gradient blocks that are reduced in a
second stage (`cuComputePartGradGammaBeta` → `cuComputeGradInput`).

Design delta: the reference saves (mean, invvar) as residuals; here the
backward kernel *recomputes* them from the saved input — on TPU the row
reduction is free next to the mandatory HBM re-read of ``x``, and dropping
the residual saves memory and a layout-awkward (N,) tensor.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import use_interpret

LANES = 128


def _row_block(h_padded: int, n_bufs: int) -> int:
    """Rows per grid step: keep n_bufs (R, Hp) fp32 buffers ≤ ~1 MiB each
    so double buffering stays well inside VMEM; multiple of 16 to satisfy
    the widest (bf16) tiling."""
    r = (1 << 20) // (4 * h_padded)
    r = max(16, min(256, (r // 16) * 16))
    return r


def _pad2(x2, rows, h_padded):
    n, h = x2.shape
    if n == rows and h == h_padded:
        return x2
    return jnp.pad(x2, ((0, rows - n), (0, h_padded - h)))


def _col_mask(h, h_padded, rows):
    cols = jax.lax.broadcasted_iota(jnp.int32, (rows, h_padded), 1)
    return cols < h


def _moments(x, h, mask):
    xm = jnp.where(mask, x, 0.0)
    mean = jnp.sum(xm, axis=1, keepdims=True) / h
    var = jnp.sum(jnp.where(mask, jnp.square(x - mean), 0.0),
                  axis=1, keepdims=True) / h
    return mean, var


# --- forward ----------------------------------------------------------------

def _ln_fwd_kernel(h, eps, affine, x_ref, *rest):
    if affine:
        w_ref, b_ref, y_ref = rest
    else:
        (y_ref,) = rest
    x = x_ref[:].astype(jnp.float32)
    mask = _col_mask(h, x.shape[1], x.shape[0])
    mean, var = _moments(x, h, mask)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if affine:
        y = y * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = jnp.where(mask, y, 0.0).astype(y_ref.dtype)


def _ln_forward(x2, weight, bias, eps, block_rows=None):
    n, h = x2.shape
    hp = -(-h // LANES) * LANES
    if block_rows is None:
        from apex_tpu.ops import autotune
        block_rows = autotune.tuned_rows("layer_norm", (n, h), x2.dtype)
    r = block_rows if block_rows is not None else _row_block(hp, 4)
    npad = -(-n // r) * r
    xp = _pad2(x2, npad, hp)
    affine = weight is not None

    row_spec = pl.BlockSpec((r, hp), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [row_spec]
    args = [xp]
    if affine:
        wb_spec = pl.BlockSpec((1, hp), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
        in_specs += [wb_spec, wb_spec]
        args += [_pad2(weight.reshape(1, h), 1, hp),
                 _pad2(bias.reshape(1, h), 1, hp)]

    y = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, h, eps, affine),
        grid=(npad // r,),
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((npad, hp), x2.dtype),
        interpret=use_interpret(),
    )(*args)
    return y[:n, :h]


# --- backward ---------------------------------------------------------------

def _ln_bwd_kernel(h, eps, affine, g_ref, x_ref, *rest):
    if affine:
        w_ref, dx_ref, dw_ref, db_ref = rest
    else:
        (dx_ref,) = rest
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    mask = _col_mask(h, x.shape[1], x.shape[0])
    mean, var = _moments(x, h, mask)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd

    gw = g * w_ref[:].astype(jnp.float32) if affine else g
    gw = jnp.where(mask, gw, 0.0)
    # dgrad: rstd * (gw - mean(gw) - xhat * mean(gw*xhat))
    # (`cuComputeGradInput`, `layer_norm_cuda_kernel.cu:523-650`)
    m1 = jnp.sum(gw, axis=1, keepdims=True) / h
    m2 = jnp.sum(gw * xhat, axis=1, keepdims=True) / h
    dx = rstd * (gw - m1 - xhat * m2)
    dx_ref[:] = jnp.where(mask, dx, 0.0).astype(dx_ref.dtype)
    if affine:
        gm = jnp.where(mask, g, 0.0)
        # per-block partial reductions (`cuComputePartGradGammaBeta`),
        # written into row 0 of an 8-sublane slab: Mosaic requires the
        # block's second-to-last dim be a multiple of 8 (or the full
        # array dim), so a (1, hp) partial row per grid step is not a
        # legal block — the stage-2 sum absorbs the zero rows
        rows = jax.lax.broadcasted_iota(jnp.int32, dw_ref.shape, 0)
        dw_ref[:] = jnp.where(rows == 0,
                              jnp.sum(gm * xhat, axis=0, keepdims=True),
                              0.0)
        db_ref[:] = jnp.where(rows == 0,
                              jnp.sum(gm, axis=0, keepdims=True), 0.0)


def _ln_backward(g2, x2, weight, eps, block_rows=None):
    n, h = x2.shape
    hp = -(-h // LANES) * LANES
    if block_rows is None:
        from apex_tpu.ops import autotune
        block_rows = autotune.tuned_rows("layer_norm", (n, h), x2.dtype)
    r = block_rows if block_rows is not None else _row_block(hp, 6)
    npad = -(-n // r) * r
    nblocks = npad // r
    gp = _pad2(g2, npad, hp)
    xp = _pad2(x2, npad, hp)
    affine = weight is not None

    row_spec = pl.BlockSpec((r, hp), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    part_spec = pl.BlockSpec((8, hp), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    in_specs = [row_spec, row_spec]
    args = [gp, xp]
    out_specs = [row_spec]
    out_shapes = [jax.ShapeDtypeStruct((npad, hp), x2.dtype)]
    if affine:
        in_specs.append(pl.BlockSpec((1, hp), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM))
        args.append(_pad2(weight.reshape(1, h), 1, hp))
        out_specs += [part_spec, part_spec]
        out_shapes += [jax.ShapeDtypeStruct((nblocks * 8, hp),
                                            jnp.float32)] * 2

    res = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, h, eps, affine),
        grid=(nblocks,),
        in_specs=in_specs,
        out_specs=tuple(out_specs) if affine else out_specs[0],
        out_shape=tuple(out_shapes) if affine else out_shapes[0],
        interpret=use_interpret(),
    )(*args)
    if affine:
        dx, dw_part, db_part = res
        # stage-2 reduction of the partials
        dw = jnp.sum(dw_part, axis=0)[:h]
        db = jnp.sum(db_part, axis=0)[:h]
        return dx[:n, :h], dw, db
    return res[:n, :h], None, None


# --- public API -------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm_affine(x, weight, bias, eps=1e-5):
    """LayerNorm over the last dim with affine params — the
    ``fused_layer_norm_affine`` entry (`apex/normalization/
    fused_layer_norm.py:12-69`). Weight/bias grads come back in fp32."""
    shape = x.shape
    y = _ln_forward(x.reshape(-1, shape[-1]), weight, bias, eps)
    return y.reshape(shape)


def _flna_fwd(x, weight, bias, eps):
    return fused_layer_norm_affine(x, weight, bias, eps), (x, weight)


def _flna_bwd(eps, res, g):
    x, weight = res
    shape = x.shape
    dx, dw, db = _ln_backward(g.reshape(-1, shape[-1]),
                              x.reshape(-1, shape[-1]), weight, eps)
    return (dx.reshape(shape), dw.astype(weight.dtype),
            db.astype(weight.dtype))


fused_layer_norm_affine.defvjp(_flna_fwd, _flna_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def fused_layer_norm(x, eps=1e-5):
    """Non-affine LayerNorm (`fused_layer_norm.py:71-100`)."""
    shape = x.shape
    return _ln_forward(x.reshape(-1, shape[-1]), None, None,
                       eps).reshape(shape)


def _fln_fwd(x, eps):
    return fused_layer_norm(x, eps), x


def _fln_bwd(eps, x, g):
    shape = x.shape
    dx, _, _ = _ln_backward(g.reshape(-1, shape[-1]),
                            x.reshape(-1, shape[-1]), None, eps)
    return (dx.reshape(shape),)


fused_layer_norm.defvjp(_fln_fwd, _fln_bwd)


def layer_norm_reference(x, weight=None, bias=None, eps=1e-5):
    """Pure-jnp reference (the CPU fallback `F.layer_norm` path,
    `fused_layer_norm.py:57-62`) — also the numeric oracle in tests."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype)


class FusedLayerNorm:
    """flax module mirror of ``apex.normalization.FusedLayerNorm``
    (`fused_layer_norm.py:70-165`)."""

    def __new__(cls, normalized_shape, eps=1e-5, elementwise_affine=True):
        import flax.linen as nn

        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        h = int(np.prod(normalized_shape))

        class _FusedLayerNorm(nn.Module):
            @nn.compact
            def __call__(self, x):
                if elementwise_affine:
                    w = self.param("scale", nn.initializers.ones, (h,),
                                   jnp.float32)
                    b = self.param("bias", nn.initializers.zeros, (h,),
                                   jnp.float32)
                    return fused_layer_norm_affine(x, w, b, eps)
                return fused_layer_norm(x, eps)

        return _FusedLayerNorm()
