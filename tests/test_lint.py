"""apexlint — jaxpr/HLO static-analysis pass suite.

One seeded-violation fixture per rule (a small jaxpr / HLO module that
triggers exactly its rule) plus a negative twin that must NOT fire —
the per-rule contract ISSUE 5 demands — and the integration claims:

- the donation rule's wasted-bytes estimate for the PRE-fix
  ``prof_bert.py``-structure step (undonated) agrees with
  ``prof.memory_report``'s params+optimizer_state attribution within
  5%, and the donated twin lints clean;
- the post-fix flagship-structure steps produce zero error-severity
  findings (the no-false-positive guard behind the
  ``run_tier1.sh --smoke`` gate);
- Report plumbing: baseline suppression round-trip, lint JSONL events
  through ``MetricsLogger(lint_sink=...)`` validating under
  ``check_metrics_schema.py --kind lint`` (in-process and subprocess);
- the two ``lint/*`` compile-check cases run as registered.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, lint, models, monitor, prof
from apex_tpu.lint import findings as F
from apex_tpu.optim import FusedSGD

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_SCHEMA_SCRIPT = os.path.join(_REPO_ROOT, "scripts",
                              "check_metrics_schema.py")


def _rules(findings):
    return sorted({f.rule for f in findings})


# --- jaxpr pass: seeded violation + negative twin per rule -------------------

class TestRngKeyReuse:
    def test_fires_on_raw_key_reuse(self):
        def f(key, x):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b + x

        fs = lint.lint_jaxpr(f, jax.random.PRNGKey(0), jnp.zeros(4))
        hits = [f_ for f_ in fs if f_.rule == "rng-key-reuse"]
        assert len(hits) == 1 and hits[0].count == 2
        assert hits[0].severity == "error"

    def test_fires_on_typed_key_reuse(self):
        def f(key, x):
            return (jax.random.normal(key, (4,))
                    + jax.random.uniform(key, (4,)) + x)

        fs = lint.lint_jaxpr(f, jax.random.key(0), jnp.zeros(4))
        assert "rng-key-reuse" in _rules(fs)

    def test_split_then_use_is_reuse(self):
        # splitting a key and ALSO drawing from it is the classic bug
        def f(key):
            k1, _ = jax.random.split(key)
            return jax.random.normal(key, (2,)) + jax.random.normal(
                k1, (2,))

        assert "rng-key-reuse" in _rules(
            lint.lint_jaxpr(f, jax.random.PRNGKey(0)))

    def test_clean_split_does_not_fire(self):
        def f(key, x):
            k1, k2 = jax.random.split(key)
            return (jax.random.normal(k1, (4,))
                    + jax.random.uniform(k2, (4,)) + x)

        assert "rng-key-reuse" not in _rules(
            lint.lint_jaxpr(f, jax.random.PRNGKey(0), jnp.zeros(4)))


class TestF64Creep:
    def test_fires_on_f64(self):
        from jax.experimental import enable_x64
        with enable_x64():
            fs = lint.lint_jaxpr(
                lambda x: jnp.sum(x.astype(jnp.float64)),
                jnp.zeros(4, jnp.float32))
        hits = [f for f in fs if f.rule == "f64-creep"]
        assert len(hits) == 1 and hits[0].severity == "error"
        assert hits[0].count >= 1

    def test_clean_f32_does_not_fire(self):
        fs = lint.lint_jaxpr(lambda x: jnp.sum(x * 2), jnp.zeros(4))
        assert "f64-creep" not in _rules(fs)


class TestFp32MatmulInAmp:
    def test_fires_under_half_policy(self):
        pol = amp.Policy.from_opt_level("O2")

        def mm(a, b):
            return a @ b

        fs = lint.lint_jaxpr(mm, jnp.zeros((8, 128)),
                             jnp.zeros((128, 128)), policy=pol)
        hits = [f for f in fs if f.rule == "fp32-matmul-in-amp"]
        assert len(hits) == 1 and hits[0].severity == "warning"

    def test_bf16_matmul_does_not_fire(self):
        pol = amp.Policy.from_opt_level("O2")

        def mm(a, b):
            return a @ b

        fs = lint.lint_jaxpr(
            mm, jnp.zeros((8, 128), jnp.bfloat16),
            jnp.zeros((128, 128), jnp.bfloat16), policy=pol)
        assert "fp32-matmul-in-amp" not in _rules(fs)

    def test_inactive_without_policy(self):
        def mm(a, b):
            return a @ b

        fs = lint.lint_jaxpr(mm, jnp.zeros((8, 128)),
                             jnp.zeros((128, 128)))
        assert "fp32-matmul-in-amp" not in _rules(fs)


class TestHostCallback:
    def test_fires_on_debug_print(self):
        def f(x):
            jax.debug.print("x={x}", x=x.sum())
            return x * 2

        fs = lint.lint_jaxpr(f, jnp.ones(4))
        hits = [f_ for f_ in fs if f_.rule == "host-callback-in-step"]
        assert len(hits) == 1 and hits[0].severity == "error"
        assert hits[0].op == "debug_callback"

    def test_clean_step_does_not_fire(self):
        fs = lint.lint_jaxpr(lambda x: x * 2, jnp.ones(4))
        assert fs == []


# --- HLO pass: seeded violation + negative twin per rule ---------------------

def _toy_amp_step():
    """Small Amp O2 train step with real params/opt-state arg paths."""
    pol = amp.Policy.from_opt_level("O2")
    params = {"w": jnp.zeros((64, 64), jnp.float32),
              "b": jnp.zeros((64,), jnp.float32)}
    amp_opt = amp.Amp(pol, FusedSGD(lr=0.1, momentum=0.9))
    state = amp_opt.init(params)
    x = jnp.zeros((8, 64))
    y = jnp.zeros((8, 64))

    def step(state, x, y):
        def loss_fn(mp):
            return jnp.mean((x @ mp["w"] + mp["b"] - y) ** 2)
        loss, grads, state, finite = amp_opt.backward(state, loss_fn)
        return amp_opt.apply_gradients(state, grads, finite), loss

    return step, state, x, y, pol


class TestDonationMiss:
    def test_fires_on_undonated_step(self):
        step, state, x, y, pol = _toy_amp_step()
        rep = lint.lint_step(jax.jit(step), state, x, y, policy=pol)
        hits = rep.by_rule("donation-miss")
        assert hits and all(h.severity == "error" for h in hits)
        # evidence: arg paths name the carried state, bytes estimated
        assert any("opt_state" in (h.scope or "") for h in hits)
        assert all((h.bytes or 0) > 0 for h in hits)

    def test_donated_step_is_clean(self):
        step, state, x, y, pol = _toy_amp_step()
        rep = lint.lint_step(jax.jit(step, donate_argnums=(0,)),
                             state, x, y, policy=pol)
        assert rep.by_rule("donation-miss") == []
        assert rep.errors == []

    def test_inference_params_not_flagged(self):
        # params that never come back out have no output to donate
        # into — not carried state, not a finding
        params = {"w": jnp.zeros((64, 64)), "b": jnp.zeros((64,))}

        def infer(params, x):
            return x @ params["w"] + params["b"]

        rep = lint.lint_step(jax.jit(infer), params, jnp.zeros((8, 64)))
        assert rep.by_rule("donation-miss") == []


class TestImplicitResharding:
    def test_fires_on_unscoped_collective(self, mesh8):
        def step(x):
            return jax.lax.psum(x, "data")

        m = jax.jit(jax.shard_map(step, mesh=mesh8,
                                  in_specs=(P("data"),),
                                  out_specs=P("data"), check_vma=False))
        text = m.lower(jnp.ones((8, 128))).compile().as_text()
        hits = [f for f in lint.lint_hlo_text(text)
                if f.rule == "implicit-resharding"]
        assert len(hits) == 1
        assert hits[0].severity == "warning"
        assert hits[0].op == "all-reduce"
        assert (hits[0].bytes or 0) > 0      # wire-byte cost attached

    def test_known_scope_not_flagged(self, mesh8):
        from apex_tpu.trace.spans import span

        def step(x):
            with span("ddp/sync_gradients", kind="collective"):
                return jax.lax.psum(x, "data")

        m = jax.jit(jax.shard_map(step, mesh=mesh8,
                                  in_specs=(P("data"),),
                                  out_specs=P("data"), check_vma=False))
        text = m.lower(jnp.ones((8, 128))).compile().as_text()
        assert [f for f in lint.lint_hlo_text(text)
                if f.rule == "implicit-resharding"] == []

    def test_zero_scatter_gather_scopes_known(self, mesh8):
        # the ZeRO optimizer's own collectives run under
        # zero/grad_scatter / zero/param_gather spans — planned, clean
        from apex_tpu.optim.distributed import (_all_gather_shard,
                                                _reduce_scatter_mean)

        def step(x):
            s = _reduce_scatter_mean(x, "data", 8)
            return _all_gather_shard(s, "data")

        m = jax.jit(jax.shard_map(step, mesh=mesh8, in_specs=(P(),),
                                  out_specs=P(), check_vma=False))
        text = m.lower(jnp.ones((64, 128))).compile().as_text()
        assert [f for f in lint.lint_hlo_text(text)
                if f.rule == "implicit-resharding"] == []


class TestHostTransfer:
    def test_fires_on_compiled_callback(self):
        def f(x):
            jax.debug.print("x={x}", x=x.sum())
            return x * 2

        rep = lint.lint_step(f, jnp.ones(4))
        hits = rep.by_rule("host-transfer")
        assert hits and hits[0].severity == "error"

    def test_clean_step_has_no_host_traffic(self):
        rep = lint.lint_step(lambda x: x * 2, jnp.ones(4))
        assert rep.by_rule("host-transfer") == []


class TestTilePadding:
    def test_fires_on_off_grid_dot(self):
        def mm(a, b):
            return a @ b

        text = prof.hlo.compiled_hlo(mm, jnp.zeros((9, 100)),
                                     jnp.zeros((100, 130)))
        hits = [f for f in lint.lint_hlo_text(text)
                if f.rule == "tile-padding"]
        assert hits
        assert all((f.bytes or 0) > 0 for f in hits)
        assert all(f.severity in ("info", "warning") for f in hits)

    def test_aligned_dot_does_not_fire(self):
        def mm(a, b):
            return a @ b

        text = prof.hlo.compiled_hlo(mm, jnp.zeros((8, 128)),
                                     jnp.zeros((128, 128)))
        assert [f for f in lint.lint_hlo_text(text)
                if f.rule == "tile-padding"] == []


# --- donation rule vs memory_report: the 5% agreement claim ------------------

def _bert_style_step(layers=2, hidden=64, heads=2, vocab=1000,
                     batch=2, seq=32):
    """The BERT-LAMB step at test scale — the SAME construction the
    bench row / apexlint flagship / prof_bert.py share
    (bench._bert_step_builder), with a tiny encoder."""
    import bench
    enc = models.BertEncoder(vocab, hidden=hidden, layers=layers,
                             heads=heads, max_len=seq * 2)
    step, state, (toks, labels), policy, _enc, _vars = \
        bench._bert_step_builder(batch, seq, encoder=enc, vocab=vocab)
    return step, state, toks, labels, policy


class TestDonationVsMemoryReport:
    def test_prefix_wasted_bytes_agree_within_5pct(self):
        """The PRE-fix (undonated) prof_bert-structure step: the
        donation rule's wasted-bytes total must agree with the
        memory_report params+optimizer_state attribution within 5% —
        both read the same carried-state buffers off the same compiled
        module."""
        step, state, toks, labels, pol = _bert_style_step()
        compiled = jax.jit(step).lower(state, toks, labels).compile()
        rep = lint.lint_step(step, state, toks, labels, policy=pol,
                             compiled=compiled, min_donation_bytes=0)
        wasted = rep.wasted_bytes("donation-miss")
        assert wasted > 0
        mrep = prof.memory_report(compiled)
        attr = (mrep.classes["params"]
                + mrep.classes["optimizer_state"])
        assert attr > 0
        assert abs(wasted - attr) / attr < 0.05, (wasted, attr)

    @pytest.mark.slow       # second full BERT-structure compile (~15s);
    def test_postfix_step_lints_clean(self):     # smoke lints full-size
        step, state, toks, labels, pol = _bert_style_step()
        rep = lint.lint_step(jax.jit(step, donate_argnums=(0,)),
                             state, toks, labels, policy=pol)
        assert rep.errors == [], rep.table()


# --- no-false-positive guard: flagship-structure steps -----------------------

class TestFlagshipClean:
    @pytest.mark.slow       # ResNet-50 compile ~35s on XLA:CPU; the
    # full-size flagship guard is the run_tier1.sh --smoke apexlint
    # gate (zero error-severity findings, --fail-on error)
    def test_resnet_o2_structure_lints_clean(self):
        """The bench flagship step structure (ResNet + amp O2 +
        FusedSGD + donated carried state) at test scale: zero
        error-severity findings — the guard behind the smoke gate's
        full-size run."""
        import bench
        step, (state, batch_stats), (x, y) = bench._resnet_step_builder(
            4, 32, "O2")
        rep = lint.lint_step(jax.jit(step, donate_argnums=(0, 1)),
                             state, batch_stats, x, y,
                             policy=amp.Policy.from_opt_level("O2"))
        assert rep.errors == [], rep.table()


# --- precision pass (APX3xx): seeded violation + negative twin per rule ------

def _pp(fn, *args, policy=None):
    """Trace + precision-analyze; returns the findings list."""
    return lint.precision_analysis(
        jax.make_jaxpr(fn)(*args), policy=policy).findings


def _by(findings, rule):
    return [f for f in findings if f.rule == rule]


class TestUnscaledNarrowCast:                               # APX301
    def test_fires_on_raw_fp8_cast(self):
        fs = _pp(lambda x: x.astype(jnp.float8_e4m3fn),
                 jnp.ones((16,), jnp.float32))
        hits = _by(fs, "unscaled-narrow-cast")
        assert len(hits) == 1 and hits[0].severity == "error"
        assert hits[0].dtype_from == "fp32"
        assert hits[0].dtype_to == "fp8_e4m3"
        assert hits[0].scale_provenance == "unscaled"

    def test_site_scaled_cast_is_clean(self):
        # the O4 scaled-cast recipe: a dominating scale multiply
        fs = _pp(lambda x, s: (x * s).astype(jnp.float8_e4m3fn),
                 jnp.ones((16,), jnp.float32), jnp.float32(64.0))
        assert _by(fs, "unscaled-narrow-cast") == []

    def test_loss_scaled_fp8_cast_still_fires(self):
        # a global loss scale is NOT a per-site scale: fp8 exponents
        # need placing per site — provenance names the distinction
        def f(params, x, s):
            def loss_fn(p):
                return jnp.mean((x @ p) ** 2) * s
            return jax.grad(loss_fn)(params).astype(jnp.float8_e5m2)
        fs = _pp(f, jnp.ones((4, 4), jnp.float32),
                 jnp.ones((8, 4), jnp.float32), jnp.float32(1024.0))
        hits = _by(fs, "unscaled-narrow-cast")
        assert hits and hits[0].severity == "error"
        assert hits[0].scale_provenance == "loss-scaled"

    def test_fp16_warning_only_without_loss_scaling(self):
        def f(x):
            return x.astype(jnp.float16)
        x = jnp.ones((16,), jnp.float32)
        fs = _pp(f, x)                         # no policy: warning
        hits = _by(fs, "unscaled-narrow-cast")
        assert len(hits) == 1 and hits[0].severity == "warning"
        pol = amp.Policy.from_opt_level("O3")  # loss-scaled: clean
        assert pol.uses_loss_scaling
        assert _by(_pp(f, x, policy=pol), "unscaled-narrow-cast") == []

    def test_bf16_cast_exempt(self):
        fs = _pp(lambda x: x.astype(jnp.bfloat16),
                 jnp.ones((16,), jnp.float32))
        assert _by(fs, "unscaled-narrow-cast") == []


class TestDoubleRounding:                                   # APX302
    def test_fires_on_chained_narrowing(self):
        def f(x, s):
            y = x.astype(jnp.bfloat16)         # round 1 (f32 -> bf16)
            return (y * s.astype(jnp.bfloat16)).astype(
                jnp.float8_e4m3fn)             # round 2, scaled
        fs = _pp(f, jnp.ones((16,), jnp.float32), jnp.float32(8.0))
        hits = _by(fs, "double-rounding")
        assert len(hits) == 1 and hits[0].severity == "warning"
        assert hits[0].dtype_from == "bf16"
        assert hits[0].dtype_to == "fp8_e4m3"

    def test_round_trip_is_clean(self):
        # bf16 -> f32 -> bf16 destroys nothing new
        fs = _pp(lambda x: x.astype(jnp.float32).astype(jnp.bfloat16),
                 jnp.ones((16,), jnp.bfloat16))
        assert _by(fs, "double-rounding") == []

    def test_arithmetic_resets_depth(self):
        # a sum of rounded values is a new quantity: one narrowing of
        # it is a single rounding
        def f(x, y):
            a = x.astype(jnp.bfloat16) + y.astype(jnp.bfloat16)
            return a.astype(jnp.float32).astype(jnp.bfloat16)
        fs = _pp(f, jnp.ones((16,), jnp.float32),
                 jnp.ones((16,), jnp.float32))
        assert _by(fs, "double-rounding") == []


def _leaky_grad_step(unscale):
    def step(params, x, scale):
        def loss_fn(p):
            return jnp.mean((x @ p) ** 2) * scale   # scale_loss shape
        g = jax.grad(loss_fn)(params)
        if unscale:
            inv = (1.0 / scale).astype(jnp.float32)
            g = g.astype(jnp.float32) * inv         # unscale_grads
        return params - 0.1 * g
    return (step, jnp.ones((4, 4), jnp.float32),
            jnp.ones((8, 4), jnp.float32), jnp.float32(1024.0))


class TestScaleLeak:                                        # APX303
    def test_fires_when_unscale_missing(self):
        step, p, x, s = _leaky_grad_step(unscale=False)
        hits = _by(_pp(step, p, x, s), "scale-leak")
        assert hits and all(h.severity == "error" for h in hits)
        assert hits[0].scale_provenance == "loss-scaled"

    def test_unscaled_twin_is_clean(self):
        step, p, x, s = _leaky_grad_step(unscale=True)
        assert _by(_pp(step, p, x, s), "scale-leak") == []

    def test_one_unscaled_path_still_fires(self):
        # the unscale must happen on EVERY path: taint joins as union
        def f(pred, x, s):
            _ = jnp.sum(x) * s                      # mint the token
            return jax.lax.cond(pred, lambda: x * s, lambda: x)
        fs = _pp(f, jnp.asarray(True), jnp.ones((8,), jnp.float32),
                 jnp.float32(128.0))
        assert _by(fs, "scale-leak")

    def test_scalar_outputs_exempt(self):
        # the scaled loss / scaler-state update are scalar and benign
        def f(x, s):
            return jnp.sum(x) * s
        fs = _pp(f, jnp.ones((8,), jnp.float32), jnp.float32(2.0))
        assert _by(fs, "scale-leak") == []


class TestMasterWeightViolation:                            # APX304
    def _update(self):
        def f(params, g):
            return params - 0.1 * g
        return (f, jnp.ones((32, 32), jnp.bfloat16),
                jnp.ones((32, 32), jnp.bfloat16))

    def test_o2_half_update_is_error(self):
        f, p, g = self._update()
        hits = _by(_pp(f, p, g, policy=amp.Policy.from_opt_level("O2")),
                   "master-weight-violation")
        assert len(hits) == 1 and hits[0].severity == "error"
        assert hits[0].dtype_from == "bf16"
        assert hits[0].dtype_to == "fp32"

    def test_o3_half_update_is_info(self):
        # pure-half is O3's documented design: advisory, not error
        f, p, g = self._update()
        hits = _by(_pp(f, p, g, policy=amp.Policy.from_opt_level("O3")),
                   "master-weight-violation")
        assert len(hits) == 1 and hits[0].severity == "info"

    def test_no_policy_silent(self):
        f, p, g = self._update()
        assert _by(_pp(f, p, g), "master-weight-violation") == []

    def test_master_chain_twin_is_clean(self):
        def f(master32, g16):
            new = master32 - 0.1 * g16.astype(jnp.float32)
            return new.astype(jnp.bfloat16), new
        fs = _pp(f, jnp.ones((32, 32), jnp.float32),
                 jnp.ones((32, 32), jnp.bfloat16),
                 policy=amp.Policy.from_opt_level("O2"))
        assert _by(fs, "master-weight-violation") == []


class TestHalfAccumulation:                                 # APX305
    def test_fp16_dot_fires(self):
        fs = _pp(lambda a, b: a @ b,
                 jnp.ones((4, 4), jnp.float16), jnp.ones((4, 4),
                                                         jnp.float16))
        hits = _by(fs, "half-accumulation")
        assert len(hits) == 1 and hits[0].severity == "warning"

    def test_widened_dot_is_clean(self):
        def f(a, b):
            return jax.lax.dot_general(
                a, b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        fs = _pp(f, jnp.ones((4, 4), jnp.float16),
                 jnp.ones((4, 4), jnp.float16))
        assert _by(fs, "half-accumulation") == []

    def test_bf16_dot_exempt(self):
        # the MXU widens bf16 dot accumulation in hardware
        fs = _pp(lambda a, b: a @ b,
                 jnp.ones((4, 4), jnp.bfloat16),
                 jnp.ones((4, 4), jnp.bfloat16))
        assert _by(fs, "half-accumulation") == []

    def test_fp16_accumulating_sum_fires(self):
        # cumsum keeps the operand dtype (also exercises the pjit
        # sub-jaxpr walk: jnp.cumsum traces as a nested jaxpr);
        # NB ``jnp.sum`` auto-widens to f32 even with ``dtype=f16``
        fs = _pp(lambda a: jnp.cumsum(a), jnp.ones((64,), jnp.float16))
        hits = _by(fs, "half-accumulation")
        assert hits and hits[0].severity == "warning"
        assert hits[0].op == "cumsum"

    def test_bf16_sum_is_info(self):
        # plain sum chains DO accumulate bf16 (unlike the MXU dot)
        fs = _pp(lambda a: jnp.cumsum(a), jnp.ones((64,), jnp.bfloat16))
        hits = _by(fs, "half-accumulation")
        assert hits and hits[0].severity == "info"

    def test_widened_sum_is_clean(self):
        fs = _pp(lambda a: jnp.sum(a, dtype=jnp.float32),
                 jnp.ones((64,), jnp.bfloat16))
        assert _by(fs, "half-accumulation") == []


def _fixture_report():
    from apex_tpu.monitor import numerics as nx
    path = os.path.join(_REPO_ROOT, "tests", "fixtures",
                        "bert_numerics_stats.json")
    with open(path) as f:
        return nx.precision_report(nx.stats_from_json(f.read()))


def _collective(dtype, scope="ddp/sync_gradients",
                opcode="all-reduce"):
    from apex_tpu.lint.spmd_pass import CollectiveInstr
    return CollectiveInstr(index=0, name=f"{opcode}.1", opcode=opcode,
                           channel_id=1, replica_groups=((0, 1),),
                           dtypes=(dtype,), bytes=1 << 20, scope=scope,
                           use_global_ids=False)


class TestWireDtypeUnsafe:                                  # APX306
    def _bf16_required(self):
        import dataclasses as dc
        rep = _fixture_report()
        rows = [dc.replace(r, required_dtype="bf16")
                for r in rep.rows[:3]]

        class _R:
            def __init__(self, rows):
                self.rows = rows

            def fp8_candidates(self, k=None):
                return []
        return _R(rows)

    def test_fires_on_narrow_wire(self):
        hits = lint.wire_dtype_findings(
            [_collective("f8e4m3fn")], self._bf16_required())
        assert len(hits) == 1 and hits[0].severity == "error"
        assert hits[0].id == "APX306"
        assert hits[0].dtype_from == "fp8_e4m3"
        assert hits[0].dtype_to == "bf16"
        assert hits[0].count == 3

    def test_committed_fixture_bf16_wire_is_clean(self):
        # the committed BERT fixture measures every site fp8-safe: a
        # bf16 grad sync is wide enough for all of them
        assert lint.wire_dtype_findings(
            [_collective("bf16")], _fixture_report()) == []

    def test_int8_wire_exempt(self):
        # the hierarchical int8 EF sync carries error feedback by
        # design — non-float wires are not precision subjects
        assert lint.wire_dtype_findings(
            [_collective("s8")], self._bf16_required()) == []

    def test_non_reduction_collectives_exempt(self):
        assert lint.wire_dtype_findings(
            [_collective("f8e4m3fn", opcode="all-gather")],
            self._bf16_required()) == []


class TestMisScaledToyAtEveryOptLevel:
    """Acceptance pin: a deliberately mis-scaled fp8-cast toy program
    — scaled loss, gradient cast to fp8 with no per-site scale, no
    unscale before commit — is caught by APX301 AND APX303 at every
    opt level (both rules are policy-independent by design)."""

    @pytest.mark.parametrize("lv", ["O0", "O1", "O2", "O3"])
    def test_caught(self, lv):
        def bad_step(params, x, scale):
            def loss_fn(p):
                return jnp.mean((x @ p) ** 2) * scale
            g = jax.grad(loss_fn)(params)
            g8 = g.astype(jnp.float8_e4m3fn)
            return params - 0.1 * g8.astype(jnp.float32)
        rep = lint.lint_step(
            bad_step, jnp.ones((4, 4), jnp.float32),
            jnp.ones((8, 4), jnp.float32), jnp.float32(1024.0),
            policy=amp.Policy.from_opt_level(lv),
            rules=("unscaled-narrow-cast", "scale-leak"))
        assert rep.by_rule("unscaled-narrow-cast"), rep.table()
        assert rep.by_rule("scale-leak"), rep.table()
        assert all(f.severity == "error" for f in rep.findings)


class TestAmpStepPrecisionClean:
    """No-false-positive guard: the real Amp machinery (scale_loss /
    unscale_grads / master-weight plumbing) certifies clean at every
    opt level — the fast-scale twin of the run_tier1.sh
    ``--opt-level all`` flagship sweep."""

    @pytest.mark.parametrize("lv", ["O0", "O1", "O2", "O3"])
    def test_toy_amp_step_has_no_precision_errors(self, lv):
        pol = amp.Policy.from_opt_level(lv)
        params = {"w": jnp.zeros((64, 64), jnp.float32),
                  "b": jnp.zeros((64,), jnp.float32)}
        amp_opt = amp.Amp(pol, FusedSGD(lr=0.1, momentum=0.9))
        state = amp_opt.init(params)
        x = jnp.zeros((8, 64))
        y = jnp.zeros((8, 64))

        def step(state, x, y):
            def loss_fn(mp):
                return jnp.mean((x @ mp["w"] + mp["b"] - y) ** 2)
            loss, grads, state, finite = amp_opt.backward(state,
                                                          loss_fn)
            return amp_opt.apply_gradients(state, grads, finite), loss

        fs = _pp(step, state, x, y, policy=pol)
        errors = [f for f in fs if f.severity == "error"]
        assert errors == [], errors


class TestPrecisionPreflight:
    def _clean_step(self):
        step, p, x, s = _leaky_grad_step(unscale=True)
        return jax.make_jaxpr(step)(p, x, s)

    def test_candidate_sites_pin_against_committed_fixture(self):
        # CI pin: the preflight's candidate-site set must equal the
        # committed fixture's measured site set (diff == empty) on a
        # statically-clean program — all 84 castable, ranked
        rep = _fixture_report()
        pf = lint.precision_preflight(self._clean_step(), report=rep)
        assert pf.blocking == []
        assert len(pf.rows) == len(rep.rows) == 84
        assert {r["site"] for r in pf.candidates} \
            == {r.site for r in rep.rows}
        ranks = [lint.DTYPE_NAMES.index(r["required_dtype"])
                 for r in pf.rows]
        assert ranks == sorted(ranks)
        assert "statically castable" in pf.table()

    def test_static_errors_block_every_candidate(self):
        bad = jax.make_jaxpr(
            lambda x: x.astype(jnp.float8_e4m3fn))(
                jnp.ones((8,), jnp.float32))
        pf = lint.precision_preflight(bad, report=_fixture_report())
        assert pf.blocking == ["APX301"]
        assert pf.candidates == [] and len(pf.rows) == 84
        assert "blocked by: APX301" in pf.table()

    def test_hlo_join_blocks_on_wire(self):
        # a narrow-wire APX306 error (static x measured join) blocks
        # the preflight exactly like a trace-side error
        import dataclasses as dc
        rep = _fixture_report()
        rep = dc.replace(rep, rows=[
            dc.replace(r, required_dtype="bf16") for r in rep.rows])
        hlo = ('HloModule m\nENTRY e {\n'
               '  p = f8e4m3fn[8]{0} parameter(0)\n'
               '  ROOT r = f8e4m3fn[8]{0} all-reduce(p), channel_id=1,'
               ' replica_groups={{0,1}}, to_apply=add,'
               ' metadata={op_name="ddp/sync_gradients"}\n}\n')
        from apex_tpu.lint.spmd_pass import extract_collective_schedule
        assert extract_collective_schedule(hlo)      # parser saw it
        pf = lint.precision_preflight(self._clean_step(), report=rep,
                                      hlo_text=hlo)
        assert pf.blocking == ["APX306"]
        assert pf.candidates == []


class TestSingleSharedTrace:
    def test_lint_step_traces_exactly_once(self, monkeypatch):
        """The de-dup satellite: jaxpr pass, APX204 and the precision
        pass share ONE ``jax.make_jaxpr`` trace inside ``lint_step``
        (and zero with ``jaxpr=`` pre-made), pinned alongside the
        CompileWatcher's zero-compile guarantee for trace-only rules."""
        from apex_tpu.prof import compile_watch as cw
        cw.install()
        step, state, x, y, pol = _toy_amp_step()
        calls = []
        real = jax.make_jaxpr

        def counted(fn, *a, **k):
            calls.append(fn)
            return real(fn, *a, **k)

        monkeypatch.setattr(jax, "make_jaxpr", counted)
        trace_rules = tuple(lint._JAXPR_RULES | lint._PRECISION_RULES)
        compiles0 = cw.global_counters()["compiles"]
        lint.lint_step(step, state, x, y, policy=pol,
                       rules=trace_rules)
        assert len(calls) == 1          # ONE shared trace, all passes
        assert cw.global_counters()["compiles"] == compiles0
        calls.clear()
        jaxpr = real(step)(state, x, y)
        lint.lint_step(None, policy=pol, jaxpr=jaxpr,
                       rules=trace_rules)
        assert calls == []              # pre-made trace: zero traces
        assert cw.global_counters()["compiles"] == compiles0


class TestPrecisionEvidenceContract:
    def test_dtype_fields_validated(self):
        with pytest.raises(ValueError):
            F.Finding(rule="unscaled-narrow-cast", message="m",
                      dtype_from="f32")        # HLO spelling, not ours
        with pytest.raises(ValueError):
            F.Finding(rule="scale-leak", message="m",
                      scale_provenance="scaled")

    def test_to_event_carries_evidence(self):
        f = F.Finding(rule="unscaled-narrow-cast", message="m",
                      dtype_from="fp32", dtype_to="fp8_e4m3",
                      scale_provenance="unscaled")
        ev = f.to_event()
        assert ev["dtype_from"] == "fp32"
        assert ev["dtype_to"] == "fp8_e4m3"
        assert ev["scale_provenance"] == "unscaled"

    def test_fingerprint_excludes_dtype_evidence(self):
        a = F.Finding(rule="unscaled-narrow-cast", message="m",
                      op="convert_element_type", scope="s",
                      dtype_from="fp32", dtype_to="fp8_e4m3")
        b = F.Finding(rule="unscaled-narrow-cast", message="m",
                      op="convert_element_type", scope="s",
                      dtype_from="bf16", dtype_to="fp8_e5m2")
        assert a.fingerprint() == b.fingerprint()

    def test_schema_negative_twins(self, tmp_path):
        sys.path.insert(0, os.path.join(_REPO_ROOT, "scripts"))
        try:
            import check_metrics_schema as cms
        finally:
            sys.path.pop(0)
        good = {"kind": "lint_finding", "rule": "unscaled-narrow-cast",
                "id": "APX301", "severity": "error", "message": "m",
                "dtype_from": "fp32", "dtype_to": "fp8_e4m3",
                "scale_provenance": "unscaled", "scope": None}
        assert cms.check_lint_lines([json.dumps(good)]) == []
        for field, bad_val in (("dtype_from", "f32"),
                               ("dtype_to", "float8"),
                               ("scale_provenance", "scaled")):
            bad = dict(good)
            bad[field] = bad_val
            errs = cms.check_lint_lines([json.dumps(bad)])
            assert errs, f"{field}={bad_val!r} must be rejected"


class TestDynamicsFlagshipClean:
    @pytest.mark.slow       # ResNet structural compile like the other
    def test_dynamics_step_lints_clean(self):        # flagship guards
        """The PR-19 dynamics-instrumented step (``--flagship
        dynamics``): zero error-severity findings on the empty
        baseline, like guarded/ckpt — the observatory's self-audit."""
        sys.path.insert(0, os.path.join(_REPO_ROOT, "scripts"))
        try:
            import apexlint
        finally:
            sys.path.pop(0)
        fn, args, policy, name = apexlint._build_flagship_dynamics()
        rep = lint.lint_step(fn, *args, policy=policy, fn_name=name)
        assert rep.errors == [], rep.table()


# --- Report / baseline / JSONL plumbing --------------------------------------

class TestReportPlumbing:
    def _report(self):
        def f(x):
            jax.debug.print("x={x}", x=x.sum())
            return x * 2

        return lint.lint_step(f, jnp.ones(4), fn_name="seeded")

    def test_severity_ordering_and_table(self):
        rep = self._report()
        sevs = [f.severity for f in rep.findings]
        assert sevs == sorted(sevs, key=F.SEVERITIES.index)
        t = rep.table()
        assert "APX004" in t and "fix:" in t

    def test_rule_catalog_is_stable(self):
        assert {r.id for r in F.RULES.values()} == {
            "APX001", "APX002", "APX003", "APX004",
            "APX101", "APX102", "APX103", "APX104",
            "APX201", "APX202", "APX203", "APX204",
            "APX301", "APX302", "APX303", "APX304",
            "APX305", "APX306"}
        for r in F.RULES.values():
            assert r.severity in F.SEVERITIES and r.fix and r.title

    def test_baseline_round_trip(self, tmp_path):
        rep = self._report()
        assert rep.errors
        path = tmp_path / "baseline.json"
        n = lint.save_baseline(str(path), rep)
        assert n >= 1
        baseline = lint.load_baseline(str(path))
        clean = rep.apply_baseline(baseline)
        assert len(clean) == 0 and clean.suppressed == len(rep)
        # a missing baseline file is an empty baseline (the committed
        # CI file starts empty on purpose)
        assert lint.load_baseline(str(tmp_path / "missing.json")) == []

    def test_committed_baseline_starts_empty(self):
        path = os.path.join(_REPO_ROOT, "scripts",
                            "apexlint_baseline.json")
        assert lint.load_baseline(path) == []

    def test_jsonl_round_trip_validates(self, tmp_path):
        """Report -> MetricsLogger lint channel -> JSONL ->
        check_metrics_schema --kind lint (module-level and subprocess
        CLI) — the round-trip acceptance test."""
        sys.path.insert(0, os.path.join(_REPO_ROOT, "scripts"))
        try:
            import check_metrics_schema as cms
        finally:
            sys.path.pop(0)
        rep = self._report()
        path = tmp_path / "lint.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], lint_sink=monitor.JSONLSink(str(path)))
        logger.attach_lint_report(rep)
        logger.close()
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 1 + len(rep)
        assert json.loads(lines[0])["kind"] == "lint_report"
        assert cms.check_lint_lines(lines) == []
        proc = subprocess.run(
            [sys.executable, _SCHEMA_SCRIPT, "--kind", "lint",
             str(path)], capture_output=True, text=True, cwd=_REPO_ROOT)
        assert proc.returncode == 0, proc.stderr
        # and the validator actually rejects garbage
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"kind": "lint_finding", "rule": "x"}\n')
        assert cms.check_lint_lines(
            bad.read_text().splitlines()) != []

    def test_fingerprint_excludes_bytes(self):
        a = F.Finding(rule="donation-miss", message="m", op="arg0",
                      scope="state.params", bytes=100)
        b = F.Finding(rule="donation-miss", message="m", op="arg0",
                      scope="state.params", bytes=999)
        assert a.fingerprint() == b.fingerprint()


# --- compile-check cases ------------------------------------------------------

class TestCompileCheckCases:
    def _case(self, name):
        from apex_tpu.ops import compile_check as cc
        return dict(cc.CASES)[name]

    def test_no_extra_dispatch_case(self):
        self._case("lint/no-extra-dispatch")()

    def test_precision_no_extra_dispatch_case(self):
        # precision pass + preflight leave the step's HLO bit-identical
        # (donated and undonated, with and without the measured join)
        self._case("lint/precision-no-extra-dispatch")()

    @pytest.mark.slow       # compiles 5 kernel families (~20s); also
    def test_kernel_sweep_case(self):            # runs on-device via
        self._case("lint/kernel-sweep")()        # python -m apex_tpu.ops
