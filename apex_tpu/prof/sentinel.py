"""Noise-aware perf-regression sentinel over bench JSON trajectories.

The repo accumulates one driver-captured bench row per round
(``BENCH_r01.json`` …) and, until now, a human eyeballed them. This
module is the automated gate: it extracts the perf-relevant columns
from each row (throughput, ms/step, MFU, peak HBM bytes, wire ratios,
goodput fraction, lint error counts, compile counts), builds a
**robust median/MAD baseline** per metric over the trajectory, and
judges the newest row with **direction-aware** thresholds — only the
degradation direction can regress (an MFU *gain* is never flagged), and
the threshold adapts to the trajectory's own noise:

    threshold = max(z · 1.4826 · MAD, rel_floor · |median|, abs_floor)

Rows without extractable metrics (a failed bench run commits its error
tail with ``"parsed": null``) are skipped with a note, never flagged —
a crashed bench is the driver's verdict to make, not this gate's; and
each metric needs ``min_history`` (default 2) prior finite values
before it can fire, so a brand-new column never false-positives on its
first appearance.

Accepted regressions are **waived** apexlint-style: a committed
``scripts/perf_baseline.json`` maps stable fingerprints
(``regress|<metric>``) to waiver entries, optionally carrying
``allow_to`` — the worst value the waiver covers, so a waived
regression that keeps degrading re-fires. The CLI is
``scripts/perf_sentinel.py`` (exit 1 on unwaived regression; run by
``run_tier1.sh --smoke`` over the committed trajectory, asserted with a
seeded-regression positive + no-change negative twin by
``scripts/roofline_audit.py --cpu8``). Events: ``kind="regress"``
through ``MetricsLogger(roofline_sink=…)``;
``check_metrics_schema.py --kind roofline`` validates.
"""

from __future__ import annotations

import dataclasses
import json
import statistics
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["MetricSpec", "METRICS", "Verdict", "SentinelReport",
           "extract_metrics", "load_rows", "check_row",
           "check_trajectory", "load_baseline", "save_baseline",
           "metric_specs_from_baseline"]

#: degradation directions (the schema enum): "higher" = higher is
#: better (a drop regresses), "lower" = lower is better (a rise does)
DIRECTIONS = ("higher", "lower")


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One judged bench column."""

    name: str
    path: Tuple[str, ...]         # key path into the bench JSON row
    direction: str                # "higher" | "lower" (better)
    rel_floor: float = 0.05       # min relative degradation to flag
    z: float = 3.0                # MAD z-score threshold
    abs_floor: float = 0.0        # min absolute degradation to flag
    counter: bool = False         # integer count: ANY increase flags


#: the judged columns of a default ``bench.py`` row. ``ms_per_step`` is
#: derived (batch / img_s); counters (lint/compile error counts) flag on
#: any increase — their MAD is 0 by construction on a healthy repo.
METRICS: Tuple[MetricSpec, ...] = (
    MetricSpec("device_img_s", ("value",), "higher"),
    MetricSpec("ms_per_step", ("__ms_per_step__",), "lower"),
    MetricSpec("mfu", ("extra", "mfu"), "higher"),
    MetricSpec("peak_hbm_bytes", ("extra", "peak_hbm_bytes"), "lower",
               rel_floor=0.10),
    MetricSpec("wire_ratio_bf16",
               ("extra", "ddp_comm_modes", "modes", "bf16", "ratio"),
               "lower", rel_floor=0.02),
    MetricSpec("wire_ratio_int8",
               ("extra", "ddp_comm_modes", "modes", "int8", "ratio"),
               "lower", rel_floor=0.02),
    MetricSpec("goodput_frac", ("extra", "goodput_frac"), "higher",
               rel_floor=0.10),
    # the pod observatory columns (bench _pod_row; the merge/blame/
    # drift math behind them is asserted by scripts/pod_audit.py).
    # Floors are generous: skew gauges run-to-run jitter in single-ms,
    # and drift ratios on an emulated fabric swing with load
    MetricSpec("pod_goodput", ("extra", "pod_goodput"), "higher",
               rel_floor=0.10),
    MetricSpec("comm_skew_p99", ("extra", "comm_skew_p99"), "lower",
               rel_floor=0.50, abs_floor=5.0),
    MetricSpec("comm_drift_ratio", ("extra", "comm_drift_ratio"),
               "lower", rel_floor=0.50, abs_floor=2.0),
    MetricSpec("lint_errors", ("extra", "lint_errors"), "lower",
               counter=True),
    MetricSpec("lint_spmd_errors", ("extra", "lint_spmd_errors"),
               "lower", counter=True),
    MetricSpec("sentinel_regressions", ("extra", "sentinel_regressions"),
               "lower", counter=True),
    MetricSpec("n_compiles", ("extra", "n_compiles"), "lower",
               rel_floor=0.5),
)


def metric_specs_from_baseline(path_or_data) -> List[MetricSpec]:
    """Extra judged metrics declared in the committed perf-baseline
    file — the ``"metrics"`` list next to ``"waivers"``::

        {"metrics": [{"name": "ddp_wire_bytes",
                      "path": ["extra", "ddp_comm_modes", "modes",
                               "hier_int8", "wire_bytes"],
                      "direction": "lower", "rel_floor": 0.02,
                      "reason": "..."}], ...}

    A deployment (or a PR landing a new bench column) gates custom
    metrics without forking the METRICS table; the entries are
    direction-aware and waiverable exactly like the built-ins
    (fingerprint ``regress|<name>``). A missing file or section is
    empty; malformed entries raise — a silently-dropped gate is worse
    than a loud config error."""
    if isinstance(path_or_data, str):
        try:
            with open(path_or_data) as f:
                data = json.load(f)
        except OSError:
            return []
    else:
        data = path_or_data or {}
    out: List[MetricSpec] = []
    for i, entry in enumerate(data.get("metrics", []) or []):
        if not isinstance(entry, dict) or "name" not in entry \
                or "path" not in entry or "direction" not in entry:
            raise ValueError(
                f"metrics[{i}]: want {{name, path, direction}} "
                f"(+optional rel_floor/z/abs_floor/counter), got "
                f"{entry!r}")
        if entry["direction"] not in DIRECTIONS:
            raise ValueError(f"metrics[{i}]: direction must be one of "
                             f"{DIRECTIONS}, got "
                             f"{entry['direction']!r}")
        out.append(MetricSpec(
            name=str(entry["name"]),
            path=tuple(str(k) for k in entry["path"]),
            direction=entry["direction"],
            rel_floor=float(entry.get("rel_floor", 0.05)),
            z=float(entry.get("z", 3.0)),
            abs_floor=float(entry.get("abs_floor", 0.0)),
            counter=bool(entry.get("counter", False))))
    return out


def _get_path(row: Dict, path: Tuple[str, ...]) -> Optional[float]:
    cur: Any = row
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def extract_metrics(row: Optional[Dict],
                    specs: Sequence[MetricSpec] = METRICS
                    ) -> Dict[str, float]:
    """The judged metric values present in one bench JSON row
    (missing/null columns are simply absent — older rows predate newer
    columns). ``specs`` extends the table with baseline-declared
    metrics (:func:`metric_specs_from_baseline`)."""
    if not isinstance(row, dict):
        return {}
    row = dict(row)
    value = _get_path(row, ("value",))
    batch = _get_path(row, ("extra", "batch"))
    if value and batch:
        row["__ms_per_step__"] = batch / value * 1e3
    out: Dict[str, float] = {}
    for spec in specs:
        v = _get_path(row, spec.path)
        if v is not None:
            out[spec.name] = v
    return out


def load_rows(paths: Sequence[str],
              specs: Sequence[MetricSpec] = METRICS
              ) -> List[Dict[str, Any]]:
    """Load bench rows from files, tolerating both wire formats: a
    plain ``bench.py`` JSON line, or the driver capture wrapper
    (``{"n": …, "rc": …, "parsed": {…}|null}``). Returns
    [{"path", "row" (may be None), "metrics", "note"}] in input
    order."""
    out = []
    for path in paths:
        note = None
        try:
            with open(path) as f:
                text = f.read()
            # driver files may concatenate objects; take the first
            # decodable one (the capture of this round's default bench)
            dec = json.JSONDecoder()
            obj, _ = dec.raw_decode(text.lstrip())
        except (OSError, ValueError) as e:
            out.append({"path": path, "row": None, "metrics": {},
                        "note": f"unreadable ({e})"})
            continue
        row = obj
        if isinstance(obj, dict) and "parsed" in obj:
            row = obj.get("parsed")
            if row is None:
                why = obj.get("failure_reason")
                att = obj.get("attempts")
                note = (f"no parsed bench row (rc={obj.get('rc')}"
                        + (f"; {att} probe attempts" if att else "")
                        + (f"; {why}" if why else "") + ") — skipped")
        metrics = extract_metrics(row, specs)
        if row is not None and not metrics and note is None:
            note = "no judged metrics in row — skipped"
        out.append({"path": path, "row": row, "metrics": metrics,
                    "note": note})
    return out


# --- the robust gate ---------------------------------------------------------

@dataclasses.dataclass
class Verdict:
    """One metric's judgement against its trajectory baseline."""

    metric: str
    direction: str
    latest: Optional[float]
    baseline: Optional[float]        # median over history
    mad: Optional[float]
    threshold: Optional[float]
    degradation: Optional[float]     # >0 = got worse (direction-aware)
    n_history: int
    regressed: bool = False
    waived: bool = False
    note: Optional[str] = None

    @property
    def fingerprint(self) -> str:
        return f"regress|{self.metric}"

    def to_event(self, rank: int = 0) -> Dict:
        """``kind="regress"`` event (``check_metrics_schema.py --kind
        roofline`` validates)."""
        rnd = lambda v: None if v is None else round(v, 6)
        return {"kind": "regress", "rank": rank, "metric": self.metric,
                "direction": self.direction, "latest": rnd(self.latest),
                "baseline": rnd(self.baseline), "mad": rnd(self.mad),
                "threshold": rnd(self.threshold),
                "degradation": rnd(self.degradation),
                "n_history": self.n_history,
                "regressed": bool(self.regressed),
                "waived": bool(self.waived),
                "fingerprint": self.fingerprint}


def check_row(history: Sequence[float], latest: float, spec: MetricSpec,
              *, min_history: int = 2) -> Verdict:
    """Judge one metric value against its history (median/MAD,
    direction-aware). Never flags with fewer than ``min_history``
    prior values."""
    hist = [float(v) for v in history]
    v = Verdict(metric=spec.name, direction=spec.direction,
                latest=latest, baseline=None, mad=None, threshold=None,
                degradation=None, n_history=len(hist))
    if len(hist) < min_history:
        v.note = f"insufficient history ({len(hist)} < {min_history})"
        return v
    med = statistics.median(hist)
    mad = statistics.median([abs(x - med) for x in hist])
    v.baseline, v.mad = med, mad
    degradation = (med - latest) if spec.direction == "higher" \
        else (latest - med)
    v.degradation = degradation
    if spec.counter:
        v.threshold = spec.abs_floor
        v.regressed = degradation > v.threshold
        return v
    v.threshold = max(spec.z * 1.4826 * mad,
                      spec.rel_floor * abs(med), spec.abs_floor)
    v.regressed = degradation > v.threshold
    return v


@dataclasses.dataclass
class SentinelReport:
    """All verdicts for one judged row (or a full replay)."""

    verdicts: List[Verdict]
    subject: Optional[str]            # path/name of the judged row
    notes: List[str] = dataclasses.field(default_factory=list)

    @property
    def regressions(self) -> List[Verdict]:
        return [v for v in self.verdicts if v.regressed and not v.waived]

    @property
    def ok(self) -> bool:
        return not self.regressions

    def table(self) -> str:
        lines = [f"{'metric':<22} {'dir':<7} {'latest':>12} "
                 f"{'baseline':>12} {'thresh':>10} {'verdict':<10}"]
        for v in self.verdicts:
            if v.note and v.baseline is None:
                verdict = "skip"
            elif v.regressed and v.waived:
                verdict = "WAIVED"
            elif v.regressed:
                verdict = "REGRESSED"
            else:
                verdict = "ok"
            fmt = lambda x: "-" if x is None else f"{x:.6g}"
            lines.append(f"{v.metric:<22} {v.direction:<7} "
                         f"{fmt(v.latest):>12} {fmt(v.baseline):>12} "
                         f"{fmt(v.threshold):>10} {verdict:<10}")
        for n in self.notes:
            lines.append(f"  note: {n}")
        return "\n".join(lines)

    def to_events(self, rank: int = 0) -> List[Dict]:
        return [v.to_event(rank=rank) for v in self.verdicts]


def check_trajectory(rows: Sequence[Dict[str, Any]], *,
                     waivers: Optional[Dict[str, Dict]] = None,
                     min_history: int = 2,
                     specs: Sequence[MetricSpec] = METRICS
                     ) -> SentinelReport:
    """Judge the NEWEST metric-bearing row of a trajectory against all
    earlier metric-bearing rows.

    ``rows`` as from :func:`load_rows` (each ``{"path", "metrics",
    "note"}``; plain metric dicts also accepted as
    ``{"metrics": …}``). Metric-less rows contribute notes, not
    baselines or verdicts."""
    waivers = waivers or {}
    notes = [f"{r.get('path', f'row {i}')}: {r['note']}"
             for i, r in enumerate(rows) if r.get("note")]
    bearing = [r for r in rows if r.get("metrics")]
    if not bearing:
        return SentinelReport(verdicts=[], subject=None,
                              notes=notes + ["no metric-bearing rows"])
    subject = bearing[-1]
    history = bearing[:-1]
    verdicts: List[Verdict] = []
    for spec in specs:
        latest = subject["metrics"].get(spec.name)
        if latest is None:
            continue
        hist = [r["metrics"][spec.name] for r in history
                if spec.name in r["metrics"]]
        v = check_row(hist, latest, spec, min_history=min_history)
        if v.regressed:
            waiver = waivers.get(v.fingerprint)
            if waiver is not None:
                allow_to = waiver.get("allow_to")
                better = (lambda a, b: a >= b) \
                    if spec.direction == "higher" else (lambda a, b: a <= b)
                if allow_to is None or better(latest, float(allow_to)):
                    v.waived = True
                    v.note = f"waived: {waiver.get('reason', '(no reason)')}"
        verdicts.append(v)
    return SentinelReport(verdicts=verdicts,
                          subject=subject.get("path"), notes=notes)


def replay_trajectory(rows: Sequence[Dict[str, Any]], *,
                      waivers: Optional[Dict[str, Dict]] = None,
                      min_history: int = 2,
                      specs: Sequence[MetricSpec] = METRICS
                      ) -> List[SentinelReport]:
    """Judge EVERY metric-bearing row against its prefix — the
    backtest proving the gate stays quiet on the committed history
    (``roofline_audit`` asserts it, then seeds a regression and asserts
    it fires)."""
    reports = []
    bearing_seen = 0
    for i in range(len(rows)):
        if not rows[i].get("metrics"):
            continue
        bearing_seen += 1
        if bearing_seen <= min_history:
            continue                    # nothing judgeable yet
        reports.append(check_trajectory(rows[:i + 1], waivers=waivers,
                                        min_history=min_history,
                                        specs=specs))
    return reports


# --- the committed waiver file (apexlint-baseline style) ---------------------

def load_baseline(path: str) -> Dict[str, Dict]:
    """{fingerprint: waiver} from a committed perf-baseline JSON
    (missing file = empty — the gate starts strict)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError:
        return {}
    waivers = data.get("waivers", {})
    if not isinstance(waivers, dict):
        raise ValueError(f"{path}: 'waivers' must be an object")
    return {k: (v if isinstance(v, dict) else {"reason": str(v)})
            for k, v in waivers.items()}


def save_baseline(path: str, report: SentinelReport, *,
                  reason: str = "accepted regression") -> Dict:
    """Write the current regressions as waivers (the ``--write-baseline``
    workflow): each gets ``allow_to`` = its latest value, so further
    degradation past the accepted point re-fires."""
    waivers = load_baseline(path)
    for v in report.regressions:
        waivers[v.fingerprint] = {"reason": reason,
                                  "metric": v.metric,
                                  "allow_to": v.latest,
                                  "baseline_was": v.baseline}
    data = {"version": 1, "waivers": waivers}
    try:                      # a refresh must not drop the declared
        with open(path) as f:  # extra-metrics section
            prev = json.load(f)
        if prev.get("metrics"):
            data["metrics"] = prev["metrics"]
    except (OSError, ValueError):
        pass
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data
