"""Goodput ledger + cross-rank straggler detection + link calibration.

The ISSUE-9 contract: the ledger's bucket sum closes over measured
wall time (nested/overlapping spans never double-count; joins MOVE
time, never invent it), back-dated compile spans land in ``recompile``,
a seeded persistent laggard is flagged with hysteresis and named with
its slowest span class (negative twin: a one-step blip is not), the
α–β fit recovers synthetic link parameters and survives noisy negative
slopes, the calibrated MeshModel round-trips through JSON with its
measurement provenance, the goodput/straggler/linkfit event schema
validates with negative twins, the link constant is single-sourced
(``pod_comm_budget`` imports it from ``mesh_model``), and the stdout
table shows the per-dtype logical-vs-wire split.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import monitor, trace
from apex_tpu.monitor.goodput import BUCKETS, GoodputLedger, classify_span
from apex_tpu.trace.spans import SpanEvent, StepTrace

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _schema():
    from scripts.check_metrics_schema import check_goodput_lines
    return check_goodput_lines


def _mk_step(step, wall_ms, spans):
    """StepTrace with (name, kind, t_start_s, dur_ms, depth) spans."""
    st = StepTrace(step, 0.0)
    st.dur_ms = wall_ms
    for name, kind, t0, dur, depth in spans:
        st.spans.append(SpanEvent(name, kind, t0, dur, depth))
    return st


# --- bucket classification ---------------------------------------------------

def test_classify_span():
    assert classify_span("anything", "collective") == "comm_wire"
    assert classify_span("compile/train_step", "compile") == "recompile"
    assert classify_span("data/load") == "input_wait"
    assert classify_span("input/decode") == "input_wait"
    assert classify_span("loader") == "input_wait"
    assert classify_span("fetch") == "host_callback"
    assert classify_span("host/sync") == "host_callback"
    assert classify_span("ckpt/capture") == "ckpt_stall"
    assert classify_span("guard/rewind") == "guard_rewind"
    assert classify_span("dispatch") == "compute"
    assert classify_span("fwd") == "compute"


# --- attribution sweep -------------------------------------------------------

class TestAttribution:
    def test_nested_spans_never_double_count(self):
        """A 4ms data/load nested inside a 10ms dispatch: the sweep
        gives the child its 4ms and the parent only its 6ms of self
        time — the sum closes exactly."""
        ledger = GoodputLedger(rank=0)
        st = _mk_step(0, 12.0, [
            ("data/load", "span", 0.002, 4.0, 1),   # child (ends first)
            ("dispatch", "span", 0.000, 10.0, 0),
        ])
        ledger.on_step(st)
        rec = ledger.steps[0]
        assert rec.buckets["input_wait"] == pytest.approx(4.0)
        assert rec.buckets["compute"] == pytest.approx(6.0)
        assert rec.buckets["other"] == pytest.approx(2.0)
        assert sum(rec.buckets.values()) == pytest.approx(12.0)
        assert rec.closure_error() < 1e-9

    def test_overlapping_backdated_span(self):
        """A back-dated compile span overlapping the dispatch span:
        deepest/latest wins per instant, no instant counted twice."""
        ledger = GoodputLedger(rank=0)
        st = _mk_step(0, 10.0, [
            ("dispatch", "span", 0.0, 10.0, 0),
            # back-dated over [2ms, 8ms), deeper orderless overlap
            ("compile/step", "compile", 0.002, 6.0, 1),
        ])
        ledger.on_step(st)
        rec = ledger.steps[0]
        assert rec.buckets["recompile"] == pytest.approx(6.0)
        assert rec.buckets["compute"] == pytest.approx(4.0)
        assert sum(rec.buckets.values()) == pytest.approx(10.0)

    def test_collective_span_is_comm_wire(self):
        """Without a pod merge, all collective time is wire time; the
        exposed_comm property reads the skew+wire sum back as one
        number for pre-split consumers."""
        ledger = GoodputLedger(rank=0)
        st = _mk_step(1, 5.0, [
            ("ddp/sync_gradients", "collective", 0.0, 3.0, 0)])
        ledger.on_step(st)
        rec = ledger.steps[0]
        assert rec.buckets["comm_wire"] == pytest.approx(3.0)
        assert rec.buckets["comm_skew"] == pytest.approx(0.0)
        assert rec.exposed_comm == pytest.approx(3.0)
        assert rec.buckets["other"] == pytest.approx(2.0)

    def test_note_pod_skew_splits_wire_into_skew(self):
        """A pod-merge skew note moves charge out of comm_wire into
        comm_skew on the next on_step — closure stays exact and the
        note is clamped to the wire time actually present."""
        ledger = GoodputLedger(rank=0)
        ledger.note_pod_skew(2.0, step=1)
        st = _mk_step(1, 5.0, [
            ("ddp/sync_gradients", "collective", 0.0, 3.0, 0)])
        ledger.on_step(st)
        rec = ledger.steps[0]
        assert rec.buckets["comm_skew"] == pytest.approx(2.0)
        assert rec.buckets["comm_wire"] == pytest.approx(1.0)
        assert rec.exposed_comm == pytest.approx(3.0)
        assert sum(rec.buckets.values()) == pytest.approx(5.0)
        assert rec.closure_error() < 1e-9

    def test_note_pod_skew_clamps_to_available_wire(self):
        """An over-claimed skew (clock bug upstream) cannot push
        comm_wire negative or break closure."""
        ledger = GoodputLedger(rank=0)
        ledger.note_pod_skew(10_000.0, step=1)
        st = _mk_step(1, 5.0, [
            ("ddp/sync_gradients", "collective", 0.0, 3.0, 0)])
        ledger.on_step(st)
        rec = ledger.steps[0]
        assert rec.buckets["comm_wire"] == pytest.approx(0.0)
        assert rec.buckets["comm_skew"] == pytest.approx(3.0)
        assert sum(rec.buckets.values()) == pytest.approx(5.0)

    def test_uncovered_wall_is_other(self):
        ledger = GoodputLedger(rank=0)
        ledger.on_step(_mk_step(0, 8.0, []))
        rec = ledger.steps[0]
        assert rec.buckets["other"] == pytest.approx(8.0)
        assert rec.goodput_frac == 0.0

    def test_overattribution_breaks_closure(self):
        """Spans claiming more time than the step's wall (a clock bug)
        must FAIL the closure check, not silently normalize — the 5%
        audit exists to catch exactly this."""
        ledger = GoodputLedger(rank=0, tolerance=0.05)
        ledger.on_step(_mk_step(0, 5.0, [
            ("dispatch", "span", 0.0, 9.0, 0)]))
        ok, worst = ledger.check_closure()
        assert not ok and worst > 0.5


# --- event-channel joins -----------------------------------------------------

class TestJoins:
    def test_ckpt_stall_moves_time(self):
        """A joined stall comes OUT of the residual/compute — the sum
        still closes over the measured wall."""
        ledger = GoodputLedger(rank=0)
        ledger.note_ckpt({"kind": "ckpt_save", "step": 0,
                          "stall_ms": 3.0})
        ledger.on_step(_mk_step(0, 10.0, [
            ("dispatch", "span", 0.0, 2.0, 0)]))
        rec = ledger.steps[0]
        assert rec.buckets["ckpt_stall"] == pytest.approx(3.0)
        assert rec.buckets["other"] == pytest.approx(5.0)
        assert sum(rec.buckets.values()) == pytest.approx(10.0)

    def test_join_drains_residual_before_compute(self):
        """A stall spent OUTSIDE every span sits in the residual — the
        join must take it from `other` and leave compute's measured
        span time untouched (draining compute first would under-report
        goodput while the stall silently stayed in the residual)."""
        ledger = GoodputLedger(rank=0)
        ledger.note_ckpt({"kind": "ckpt_save", "step": 0,
                          "stall_ms": 5.0})
        ledger.on_step(_mk_step(0, 100.0, [
            ("dispatch", "span", 0.0, 90.0, 0)]))
        rec = ledger.steps[0]
        assert rec.buckets["compute"] == pytest.approx(90.0)
        assert rec.buckets["ckpt_stall"] == pytest.approx(5.0)
        assert rec.buckets["other"] == pytest.approx(5.0)
        assert rec.goodput_frac == pytest.approx(0.9)

    def test_join_never_exceeds_wall(self):
        """An oversized stall claim is clamped to the measured time —
        the ledger never invents wall clock."""
        ledger = GoodputLedger(rank=0)
        ledger.note_ckpt({"kind": "ckpt_save", "step": 0,
                          "stall_ms": 100.0})
        ledger.on_step(_mk_step(0, 4.0, []))
        rec = ledger.steps[0]
        assert rec.buckets["ckpt_stall"] == pytest.approx(4.0)
        assert sum(rec.buckets.values()) == pytest.approx(4.0)

    def test_post_fold_event_attaches_to_next_step(self):
        ledger = GoodputLedger(rank=0)
        ledger.on_step(_mk_step(0, 5.0, []))
        ledger.note_ckpt({"kind": "ckpt_save", "step": 0,
                          "stall_ms": 2.0})
        ledger.on_step(_mk_step(1, 5.0, []))
        assert ledger.steps[0].buckets["ckpt_stall"] == 0.0
        assert ledger.steps[1].buckets["ckpt_stall"] == \
            pytest.approx(2.0)

    def test_guard_join_and_non_events_ignored(self):
        ledger = GoodputLedger(rank=0)
        ledger.note_guard({"kind": "guard_rewind", "step": 0,
                           "dur_ms": 1.5})
        ledger.note_guard({"kind": "guard_anomaly", "step": 0, "z": 9.0})
        ledger.note_ckpt({"kind": "ckpt_restore", "step": 0,
                          "dur_ms": 50.0})          # not a save: ignored
        ledger.on_step(_mk_step(0, 6.0, []))
        rec = ledger.steps[0]
        assert rec.buckets["guard_rewind"] == pytest.approx(1.5)
        assert rec.buckets["ckpt_stall"] == 0.0


# --- live tracer integration -------------------------------------------------

def test_tracer_integration_and_rolling_goodput():
    tracer = trace.Tracer()
    ledger = GoodputLedger(tracer, window=8, rank=0)
    seen = []
    ledger.subscribe(seen.append)
    with tracer:
        for i in range(3):
            with trace.step(i):
                with trace.span("dispatch"):
                    time.sleep(0.003)
                with trace.span("fetch"):
                    time.sleep(0.001)
    assert len(ledger.steps) == 3 and len(seen) == 3
    ok, worst = ledger.check_closure()
    assert ok, worst
    gf = ledger.rolling_goodput()
    assert gf is not None and 0.3 < gf <= 1.0
    for rec in ledger.steps:
        assert rec.buckets["compute"] >= 2.5
        assert rec.buckets["host_callback"] >= 0.8
    table = ledger.table()
    assert "goodput" in table and "total" in table
    ev = seen[-1]
    assert ev["kind"] == "goodput" and ev["step"] == 2
    assert set(ev["buckets_ms"]) == set(BUCKETS)


def test_backdated_compile_span_lands_in_recompile():
    tracer = trace.Tracer()
    ledger = GoodputLedger(tracer, rank=0)
    with tracer:
        with trace.step(0):
            with trace.span("dispatch"):
                time.sleep(0.004)
                # what compile_watch does after a traced dispatch
                tracer.add_span_event("compile/train_step", "compile",
                                      3.0)
    rec = ledger.steps[0]
    assert rec.buckets["recompile"] >= 2.5
    assert rec.closure_error() < 0.05


# --- goodput event schema ----------------------------------------------------

class TestGoodputSchema:
    def test_valid_stream(self):
        check = _schema()
        ledger = GoodputLedger(rank=0)
        ledger.on_step(_mk_step(0, 5.0, [
            ("dispatch", "span", 0.0, 4.0, 0)]))
        lines = [json.dumps(e) for e in ledger.to_events()]
        lines.append(json.dumps(
            {"kind": "straggler", "step": 4, "rank": 2, "lag_ms": 61.0,
             "z": 12.0, "consecutive": 3, "slowest_span": "data/load",
             "span_class": "input_wait", "slowest_span_ms": 60.0,
             "n_ranks": 4, "wall_time": time.time()}))
        lines.append(json.dumps(
            {"kind": "linkfit", "link": "dcn", "axis": "data_inter",
             "alpha_us": 1500.0, "bytes_per_s": 1.4e8,
             "residual": 0.2, "n_samples": 9, "rank": 0,
             "wall_time": time.time()}))
        assert check(lines) == []

    def test_negative_twins(self):
        check = _schema()
        base_g = {"kind": "goodput", "step": 0, "rank": 0,
                  "wall_ms": 5.0, "closure_err": 0.0,
                  "buckets_ms": {"compute": 5.0}, "goodput_frac": 1.0}
        assert check([json.dumps(base_g)]) == []
        # unknown kind
        assert check([json.dumps(dict(base_g, kind="speed"))])
        # unknown bucket name
        bad = dict(base_g, buckets_ms={"gpu_time": 5.0})
        assert check([json.dumps(bad)])
        # negative wall
        assert check([json.dumps(dict(base_g, wall_ms=-1.0))])
        # missing required buckets_ms
        m = dict(base_g)
        del m["buckets_ms"]
        assert check([json.dumps(m)])
        # straggler: negative consecutive, bad link class, zero bandwidth
        s = {"kind": "straggler", "step": 1, "rank": 0, "lag_ms": 5.0,
             "z": 9.0, "consecutive": -1, "n_ranks": 4}
        assert check([json.dumps(s)])
        lf = {"kind": "linkfit", "link": "nvlink", "bytes_per_s": 1.0,
              "residual": 0.1, "n_samples": 3}
        assert check([json.dumps(lf)])
        lf2 = {"kind": "linkfit", "link": "ici", "bytes_per_s": 0,
               "residual": 0.1, "n_samples": 3}
        assert check([json.dumps(lf2)])
        # null where not allowed
        assert check([json.dumps(dict(base_g, wall_ms=None))])

    def test_logger_channel_nulls_nonfinite(self, tmp_path):
        p = tmp_path / "gp.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], goodput_sink=monitor.JSONLSink(str(p)))
        logger.record_goodput(
            {"kind": "goodput", "step": 0, "rank": 0, "wall_ms": 1.0,
             "closure_err": 0.0, "goodput_frac": float("nan"),
             "buckets_ms": {b: (float("inf") if b == "other" else 0.0)
                            for b in BUCKETS}})
        logger.close()
        rec = json.loads(p.read_text())
        assert rec["goodput_frac"] is None
        assert rec["buckets_ms"]["other"] is None


# --- straggler detection -----------------------------------------------------

def _write_beats(d, n_ranks=4, n_steps=10, slow_rank=None,
                 slow_from=5, lag_s=0.06):
    writers = [trace.HeartbeatWriter(str(d), rank=r)
               for r in range(n_ranks)]
    t0 = 1_000.0
    for step in range(n_steps):
        for r, w in enumerate(writers):
            lag = lag_s if (slow_rank == r and step >= slow_from) else 0.0
            spans = {"dispatch": 40.0,
                     "data/load": 5.0 + lag * 1e3}
            w.beat(step, dur_ms=50.0 + lag * 1e3, spans=spans,
                   wall_time=t0 + step * 0.1 + r * 1e-4 + lag)
    return writers


class TestStraggler:
    def test_heartbeat_roundtrip_skips_torn_tail(self, tmp_path):
        w = trace.HeartbeatWriter(str(tmp_path), rank=3)
        w.beat(0, dur_ms=10.0, spans={"fwd": 8.0})
        # a live writer's torn partial line must not break the reader
        with open(w.path, "a") as f:
            f.write('{"step": 1, "rank": 3, "wall_')
        beats = trace.read_heartbeats(str(tmp_path))
        assert set(beats) == {3} and set(beats[3]) == {0}
        assert beats[3][0]["spans"]["fwd"] == 8.0
        assert w.n_written == 1 and w.n_dropped == 0

    def test_persistent_laggard_named_with_span_class(self, tmp_path):
        _write_beats(tmp_path, slow_rank=2)
        det = trace.StragglerDetector(str(tmp_path), window=10,
                                      z_threshold=4.0, hysteresis=3,
                                      lag_floor_ms=1.0)
        reports = det.check()
        assert [r.rank for r in reports] == [2]
        rep = reports[0]
        assert rep.consecutive >= 3 and rep.lag_ms > 40.0
        assert rep.slowest_span == "data/load"
        assert rep.span_class == "input_wait"
        assert rep.n_ranks == 4
        ev = rep.to_event()
        assert _schema()([json.dumps(ev)]) == []

    def test_blip_not_flagged_hysteresis(self, tmp_path):
        # only the single newest step lags: below hysteresis=3
        _write_beats(tmp_path, slow_rank=2, slow_from=9)
        det = trace.StragglerDetector(str(tmp_path), window=10,
                                      hysteresis=3)
        assert det.check() == []

    def test_clock_skew_not_flagged(self, tmp_path):
        """A rank whose host wall clock runs 50 ms ahead writes late
        arrival times every step while making identical progress — the
        duration-based lag must NOT flag it (arrival comparison is
        only the fallback for duration-less beats)."""
        writers = [trace.HeartbeatWriter(str(tmp_path), rank=r)
                   for r in range(4)]
        for step in range(10):
            for r, w in enumerate(writers):
                skew = 0.050 if r == 2 else 0.0   # constant clock offset
                w.beat(step, dur_ms=50.0, spans={"dispatch": 40.0},
                       wall_time=1000.0 + step * 0.1 + skew)
        det = trace.StragglerDetector(str(tmp_path), window=10,
                                      z_threshold=4.0, hysteresis=3)
        assert det.check() == [], "constant clock offset misread as lag"

    def test_healthy_mesh_and_single_rank_quiet(self, tmp_path):
        _write_beats(tmp_path / "healthy", slow_rank=None)
        assert trace.StragglerDetector(
            str(tmp_path / "healthy")).check() == []
        solo = tmp_path / "solo"
        trace.HeartbeatWriter(str(solo), rank=0).beat(0)
        assert trace.StragglerDetector(str(solo)).check() == []

    def test_watch_feeds_watchdog_early_warning(self, tmp_path):
        _write_beats(tmp_path, slow_rank=1)
        det = trace.StragglerDetector(str(tmp_path), hysteresis=3)
        fired, stalled, events = [], [], []
        wd = trace.HangWatchdog(deadline_s=3600.0,
                                on_fire=fired.append,
                                on_stall=stalled.append)
        watch = trace.StragglerWatch(det, watchdog=wd,
                                     event_sink=events.append,
                                     renotify_s=60.0)
        assert [r.rank for r in watch.poll_once()] == [1]
        assert wd.warning_count == 1 and wd.last_warning["rank"] == 1
        assert fired and fired[0]["reason"] == "early-warning"
        assert not stalled, "early warning must never escalate"
        assert events and events[0]["kind"] == "straggler"
        # renotify window suppresses the duplicate
        watch.poll_once()
        assert wd.warning_count == 1 and len(events) == 1

    def test_tracer_subscription_writes_beats(self, tmp_path):
        tracer = trace.Tracer()
        hb = trace.HeartbeatWriter(str(tmp_path), rank=0)
        tracer.subscribe(hb.on_step)
        with tracer:
            with trace.step(0):
                with trace.span("fwd"):
                    pass
        beats = trace.read_heartbeats(str(tmp_path))
        assert 0 in beats[0] and "fwd" in beats[0][0]["spans"]


# --- link calibration --------------------------------------------------------

class TestLinkbench:
    def test_fit_recovers_synthetic_alpha_beta(self):
        from apex_tpu.monitor.linkbench import LinkSample, fit_alpha_beta
        alpha, bps = 1e-3, 2e9
        samples = [LinkSample("all_reduce", "data", b, float(b),
                              alpha + b / bps)
                   for b in (1 << 14, 1 << 17, 1 << 20, 1 << 23)]
        fit = fit_alpha_beta(samples)
        assert fit.alpha_s == pytest.approx(alpha, rel=1e-6)
        assert fit.bytes_per_s == pytest.approx(bps, rel=1e-6)
        assert fit.residual < 1e-9
        assert fit.seconds(1 << 20) == pytest.approx(
            alpha + (1 << 20) / bps, rel=1e-6)

    def test_fit_clamps_negative_slope(self):
        from apex_tpu.monitor.linkbench import LinkSample, fit_alpha_beta
        # pathological: bigger messages measured FASTER (noise)
        samples = [LinkSample("all_reduce", "data", b, float(b), t)
                   for b, t in ((1000, 2e-3), (100000, 1e-3))]
        fit = fit_alpha_beta(samples)
        assert fit.bytes_per_s > 0 and np.isfinite(fit.residual)

    @pytest.mark.slow
    def test_calibrate_cpu8_mesh(self, devices):
        from jax.sharding import Mesh

        from apex_tpu.lint.mesh_model import MeshModel, parse_mesh_spec
        from apex_tpu.monitor import linkbench

        template = parse_mesh_spec("dp2x4")
        mesh = Mesh(np.array(devices).reshape(2, 4),
                    ("data_inter", "data_intra"))
        model, fits, samples = linkbench.calibrate(
            mesh, template, sizes=(1 << 10, 1 << 13), iters=1)
        assert model.measured
        assert set(fits) == {"data_inter", "data_intra"}
        for link in ("ici", "dcn"):
            assert model.link_bytes_per_s[link] > 0
            assert model.calibration[link]["n_samples"] == 6
        # the emitted table round-trips with provenance intact
        rt = MeshModel.from_json(json.dumps(model.to_json()))
        assert rt.measured and rt.calibration == model.calibration
        assert rt.link_bytes_per_s == model.link_bytes_per_s
        events = linkbench.linkfit_events(model, rank=0)
        assert len(events) == 2
        assert _schema()([json.dumps(e) for e in events]) == []
        table = linkbench.fit_table(fits, samples)
        assert "data_intra" in table and "GB/s" in table

    def test_all_gather_moves_the_recorded_payload(self, devices):
        """The all_gather probe's GLOBAL input is the full logical
        buffer (shard_map's in_specs shard it): the gathered output
        must be elems elements, so the recorded size_bytes is really
        what the collective rebuilt — a sliced input would move N×
        fewer bytes than the LinkSample claims and corrupt the fit."""
        from jax.sharding import Mesh

        from apex_tpu.monitor.linkbench import _collective

        mesh = Mesh(np.array(devices), ("data",))
        fn = _collective("all_gather", mesh, "data")
        elems = 1024
        out = fn(jnp.arange(elems, dtype=jnp.float32))
        assert out.shape == (elems,)
        np.testing.assert_array_equal(
            np.asarray(out), np.arange(elems, dtype=np.float32))

    def test_calibrate_rejects_mismatched_mesh(self, devices):
        from jax.sharding import Mesh

        from apex_tpu.lint.mesh_model import parse_mesh_spec
        from apex_tpu.monitor import linkbench

        template = parse_mesh_spec("dp2x4")
        mesh = Mesh(np.array(devices).reshape(4, 2),
                    ("data_inter", "data_intra"))
        with pytest.raises(ValueError, match="template size"):
            linkbench.calibrate(mesh, template)

    def test_mesh_model_calibration_json(self):
        from apex_tpu.lint.mesh_model import MeshAxis, MeshModel
        mm = MeshModel((MeshAxis("s", 2, "dcn"), MeshAxis("d", 4)),
                       link_bytes_per_s={"dcn": 1.2e8},
                       calibration={"dcn": {"axis": "s",
                                            "bytes_per_s": 1.2e8,
                                            "alpha_us": 900.0,
                                            "residual": 0.1,
                                            "n_samples": 6}})
        assert mm.measured
        rt = MeshModel.from_json(mm.to_json())
        assert rt.measured and rt.calibration == mm.calibration
        plain = MeshModel((MeshAxis("d", 8),))
        assert not plain.measured
        assert "calibration" not in plain.to_json()


# --- satellites --------------------------------------------------------------

def test_link_constant_single_source():
    """scripts/pod_comm_budget.py must IMPORT its ICI constant from the
    mesh model's default table — a re-declared literal copy can
    silently diverge (the bug this pin exists to prevent)."""
    import importlib.util as _util

    from apex_tpu.lint.mesh_model import DEFAULT_LINK_BYTES_PER_S
    path = os.path.join(_REPO_ROOT, "scripts", "pod_comm_budget.py")
    spec = _util.spec_from_file_location("pod_comm_budget", path)
    pcb = _util.module_from_spec(spec)
    spec.loader.exec_module(pcb)
    assert pcb.ICI_BYTES_PER_S == DEFAULT_LINK_BYTES_PER_S["ici"]
    src = open(path).read()
    assert "ICI_BYTES_PER_S = DEFAULT_LINK_BYTES_PER_S" in src, \
        "pod_comm_budget re-declared its own link constant"
    assert "4.5e11" not in src.replace(
        "ICI_BYTES_PER_S = DEFAULT_LINK_BYTES_PER_S", ""), \
        "a literal copy of the ICI bandwidth crept back in"


def test_stdout_sink_wire_columns():
    import io

    sink = monitor.StdoutSink(stream=io.StringIO(), header_every=1)
    base = {"step": 0, "loss": 1.0, "loss_scale": 1.0, "grad_norm": 0.5,
            "skip_count": 0, "step_time_ms": 10.0,
            "throughput_steps_per_s": 100.0, "mfu": 0.5}
    sink.emit(dict(base, wire_by_dtype={"bf16": 50_000_000,
                                        "f32": 1_000_000},
                   wire_to_logical=0.5))
    out = sink.stream.getvalue()
    assert "wire" in out and "w/l" in out          # header columns
    assert "bf16:47.7M" in out                     # per-dtype split
    assert "0.50" in out                           # the ratio
    sink.emit(base)                                # statics not attached
    assert "n/a" in sink.stream.getvalue().splitlines()[-1]


def test_logger_attach_populates_wire_breakdown(tmp_path):
    """attach() must derive the per-dtype wire split off the same
    compiled HLO as the total, and flush must carry it per record with
    the wire_to_logical ratio."""
    import io

    x = jnp.ones((8, 16), jnp.float32)

    def step(m, x):
        return m.count_step(jnp.bool_(True)).record_loss(
            jnp.sum(x * x)), x

    buf = io.StringIO()
    n_logical = int(x.size * 4)
    logger = monitor.MetricsLogger(
        sinks=[monitor.JSONLSink(buf)], flush_every=1,
        logical_collective_bytes=n_logical)
    m = monitor.metrics_init()
    logger.attach(step, m, x)
    assert logger.collective_bytes_by_dtype is not None
    m, _ = jax.jit(step)(m, x)
    logger.record(m)
    logger.close()
    rec = json.loads(buf.getvalue().splitlines()[0])
    assert "wire_by_dtype" in rec and "wire_to_logical" in rec
    assert rec["logical_bytes"] == n_logical
    # single-chip step: no collectives, wire 0, ratio 0
    assert rec["collective_bytes"] == 0
    assert rec["wire_to_logical"] == 0.0
    from scripts.check_metrics_schema import check_lines
    assert check_lines(buf.getvalue().splitlines()) == []


# --- per-axis exposed-comm split (ISSUE-17) ----------------------------------

def test_comm_axes_split_joins_registry():
    """Each collective span's exposed time lands on its registry axis
    (hop sub-spans on the factored axes, not the composite parent);
    unregistered scopes land in the explicit "unknown" row; the axis
    sums equal the comm buckets exactly."""
    ledger = GoodputLedger(rank=0)
    st = _mk_step(3, 10.0, [
        ("ddp/sync_gradients/bucket00/ici", "collective", 0.000, 2.0, 1),
        ("ddp/sync_gradients/bucket00/dcn", "collective", 0.002, 1.0, 1),
        ("nobody/planned/this", "collective", 0.003, 1.0, 1),
        ("dispatch", "span", 0.004, 6.0, 0),
    ])
    ledger.on_step(st)
    rec = ledger.steps[-1]
    axes = rec.comm_axes_ms
    assert set(axes) == {"data_intra", "data_inter", "unknown"}
    assert axes["data_intra"]["wire"] == pytest.approx(2.0)
    assert axes["data_inter"]["wire"] == pytest.approx(1.0)
    assert axes["unknown"]["wire"] == pytest.approx(1.0)
    assert sum(p["wire"] for p in axes.values()) == pytest.approx(
        rec.buckets["comm_wire"])
    assert sum(p["skew"] for p in axes.values()) == pytest.approx(
        rec.buckets["comm_skew"])
    ev = rec.to_event(0)
    assert ev["comm_axes_ms"]["data_intra"]["wire"] == pytest.approx(2.0)
    assert _schema()([json.dumps(ev)]) == []
    totals = ledger.comm_axes_totals()
    assert totals["data_intra"]["wire"] == pytest.approx(2.0)


def test_comm_axes_skew_proportional():
    """A pod-skew join reclassifies each axis's wire share
    proportionally, so the per-axis sums still equal the
    comm_wire/comm_skew buckets after the move."""
    ledger = GoodputLedger(rank=0)
    ledger.note_pod_skew(1.5, step=0)
    st = _mk_step(0, 10.0, [
        ("ddp/sync_gradients/bucket00/ici", "collective", 0.000, 2.0, 1),
        ("ddp/sync_gradients/bucket00/dcn", "collective", 0.002, 1.0, 1),
    ])
    ledger.on_step(st)
    rec = ledger.steps[-1]
    assert rec.buckets["comm_skew"] == pytest.approx(1.5)
    assert rec.buckets["comm_wire"] == pytest.approx(1.5)
    axes = rec.comm_axes_ms
    # ici carried 2/3 of the wire -> 2/3 of the skew blame
    assert axes["data_intra"]["skew"] == pytest.approx(1.0)
    assert axes["data_inter"]["skew"] == pytest.approx(0.5)
    assert (axes["data_intra"]["wire"] + axes["data_intra"]["skew"]
            == pytest.approx(2.0))
    assert sum(p["wire"] for p in axes.values()) == pytest.approx(
        rec.buckets["comm_wire"])
    assert sum(p["skew"] for p in axes.values()) == pytest.approx(
        rec.buckets["comm_skew"])


def test_scope_axis_single_source():
    """The scope→axis join every per-axis consumer shares: ONE function
    (monitor.collectives.scope_axis_row) over ONE table
    (parallel.registry.COLLECTIVE_SCOPES) — a second private copy can
    silently diverge. The hop sub-span rows must precede their
    ddp/sync_gradients parent in the registry, or first-match
    resolution swallows the factored-axis attribution."""
    from apex_tpu.monitor.collectives import scope_axis_row

    assert scope_axis_row("ddp/sync_gradients/bucket03/ici") == "data_intra"
    assert scope_axis_row("ddp/sync_gradients/bucket03/dcn") == "data_inter"
    assert scope_axis_row("ddp/sync_gradients") == "data"
    assert scope_axis_row("zero/grad_scatter") == "data"
    assert scope_axis_row("nobody/planned/this") == "unknown"
    # one definition, one table, in the whole package
    defs, tables = [], []
    for root, _dirs, files in os.walk(
            os.path.join(_REPO_ROOT, "apex_tpu")):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            src = open(os.path.join(root, fname)).read()
            if "def scope_axis_row" in src:
                defs.append(fname)
            if "CollectiveScope(" in src and fname != "registry.py":
                tables.append(fname)
    assert defs == ["collectives.py"], defs
    assert tables == [], f"private collective-scope tables: {tables}"
    # the per-axis consumers route through the shared join
    gp = open(os.path.join(_REPO_ROOT, "apex_tpu", "monitor",
                           "goodput.py")).read()
    assert "scope_axis_row" in gp
    me = open(os.path.join(_REPO_ROOT, "scripts", "mesh_explain.py")).read()
    assert "collective_bytes_by_axis" in me, \
        "mesh_explain grew its own scope→axis pricing map"
