#!/usr/bin/env python
"""kernel_tune — the Pallas block-shape sweep + the asserting CI audit
of the autotuner (run by ``run_tier1.sh --smoke``; exit status is the
verdict).

Two modes:

``--update-db [--interpret]``
    Sweep every kernel family's candidate grid over the tuning shapes
    (best-of-N per candidate, compiles accounted under
    ``compile_watch.autotune_scope()``) and commit the winners to
    ``scripts/kernel_tuning_db.json`` keyed by
    ``family|dims|dtype|chip`` fingerprints. On CPU the sweep runs in
    Pallas interpret mode and the chip key is ``cpu`` — interpret wall
    clock is structural evidence (grid-step count), not a TPU claim;
    re-run on a TPU host to add on-chip entries under their own chip
    key.

``--cpu8 --interpret``
    The asserted structural audit, CPU-only:

    (a) **sweep accounting**: every family sweeps its grid in interpret
        mode and ``autotune_scope()`` reports *exactly* the sweep's
        compile count — then a steady-state consult of the freshly
        written DB re-traces with ``n_autotune_compiles`` unchanged
        (tuned dispatch is a trace-time table lookup, not a compile).
    (b) **DB round-trip**: write → reload → exact-key hit; a nearest
        miss (one row off) does NOT match.
    (c) **stale refusal**: a seeded entry whose recorded dims no longer
        re-fingerprint to its key raises ``StaleTuningEntry`` naming
        the key — refused loudly, never silently applied.
    (d) **measurable win**: at least one family's sweep shows a real
        candidate spread on CPU (the optimizer launcher's 512-row vs
        64-row block is an 8x grid-step difference in interpret mode —
        the claim is sweep→DB→dispatch plumbing, not CPU microseconds).
    (e) **committed DB**: ``scripts/kernel_tuning_db.json`` loads
        stale-free with ≥1 entry per kernel family and serves an
        exact-key hit at trace time.
    (f) **tune_report join**: DB entries join ``worst_gaps()`` off the
        committed BERT-layer fixture and name the ~549-vs-436 us
        fused-backward attention candidate as covered.
    (g) every emitted ``kind="tune"`` stream validates under
        ``check_metrics_schema.py --kind roofline``.

Usage:
  JAX_PLATFORMS=cpu python scripts/kernel_tune.py --cpu8 --interpret
  JAX_PLATFORMS=cpu python scripts/kernel_tune.py --update-db --interpret
"""

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures")
_DB_PATH = os.path.join(_REPO, "scripts", "kernel_tuning_db.json")


def _run_schema(path: str, kind: str = "roofline") -> None:
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts",
                                      "check_metrics_schema.py"),
         "--kind", kind, path],
        capture_output=True, text=True)
    assert r.returncode == 0, (
        f"schema validation failed for {path}:\n{r.stdout}{r.stderr}")


# --- the sweep shapes --------------------------------------------------------
# One representative problem shape per family. Small enough that the
# interpret-mode CI sweep stays in seconds; the same table drives
# --update-db, so the committed DB always covers what the audit expects.

def sweep_specs():
    """family -> (dims, dtype, build) where ``build(block) -> (fn, args)``
    calls the family's dispatch seam with the candidate block made
    explicit (explicit always wins over the DB, so sweeping is
    independent of whatever DB is installed)."""
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops import attention as attn
    from apex_tpu.ops import layer_norm as ln
    from apex_tpu.ops import mlp as mlp_mod
    from apex_tpu.ops import xentropy as xe
    from apex_tpu.ops import multi_tensor as mt
    from apex_tpu.ops import _dispatch

    rng = np.random.RandomState(0)
    f32 = jnp.float32

    specs = {}

    b, sq, sk, h, d = 1, 256, 256, 2, 64
    q = jnp.asarray(rng.randn(b, sq, h, d), f32)
    k = jnp.asarray(rng.randn(b, sk, h, d), f32)
    v = jnp.asarray(rng.randn(b, sk, h, d), f32)

    def build_attn(block):
        def fn(q_, k_, v_):
            return attn.flash_attention(
                q_, k_, v_, block_q=block["block_q"],
                block_k=block["block_k"])
        return fn, (q, k, v)

    specs["attention"] = ((b, sq, sk, h, d), f32, build_attn)

    n, hdim = 256, 192
    x_ln = jnp.asarray(rng.randn(n, hdim), f32)
    w_ln = jnp.ones((hdim,), f32)
    b_ln = jnp.zeros((hdim,), f32)

    def build_ln(block):
        def fn(x_, w_, b_):
            return ln._ln_forward(x_, w_, b_, 1e-5,
                                  block_rows=block["block_rows"])
        return fn, (x_ln, w_ln, b_ln)

    specs["layer_norm"] = ((n, hdim), f32, build_ln)

    nm, d0, d1, d2 = 256, 96, 128, 96
    x_mlp = jnp.asarray(rng.randn(nm, d0), f32)
    ws = (jnp.asarray(rng.randn(d0, d1) * 0.05, f32),
          jnp.asarray(rng.randn(d1, d2) * 0.05, f32))
    bs = (jnp.zeros((d1,), f32), jnp.zeros((d2,), f32))

    def build_mlp(block):
        def fn(x_, w0, w1, b0, b1):
            return mlp_mod._fused_mlp_fwd_impl(
                x_, (w0, w1), (b0, b1), "relu",
                block_rows=block["block_rows"])
        return fn, (x_mlp, *ws, *bs)

    specs["mlp"] = ((nm, d0, d1, d2), f32, build_mlp)

    nx, vocab = 128, 384
    x_xe = jnp.asarray(rng.randn(nx, vocab), f32)
    lab = jnp.asarray(rng.randint(0, vocab, nx), jnp.int32)

    def build_xe(block):
        def fn(x_, l_):
            loss, _ = xe._fwd_call(x_, l_, 0.0,
                                   block_rows=block["block_rows"])
            return loss
        return fn, (x_xe, lab)

    specs["xentropy"] = ((nx, vocab), f32, build_xe)

    nopt = 512 * 128          # one BUFFER_MULTIPLE arena buffer
    buf = jnp.asarray(rng.randn(nopt), f32)

    def build_opt(block):
        def fn(b_):
            import jax.numpy as jnp_
            out, flag = _dispatch.launch(
                mt._scale_kernel, [b_],
                outs=[("block", jnp_.float32),
                      ("scalar", jnp_.float32)],
                scalars=[2.0], block_rows=block["block_rows"])
            return out, flag
        return fn, (buf,)

    specs["optimizer"] = ((nopt,), f32, build_opt)
    return specs


def run_sweep(on_event=None):
    """Sweep every family; returns (TuningDB, per-family timed grids,
    total candidate count)."""
    from apex_tpu.ops import autotune

    db = autotune.TuningDB()
    grids = {}
    total = 0
    for family, (dims, dtype, build) in sweep_specs().items():
        timed = []
        entry = autotune.sweep_entry(
            family, dims, dtype, build,
            on_candidate=lambda blk, us: timed.append((blk, us)))
        db.add(entry)
        grids[family] = timed
        total += len(timed)
        best = min(us for _, us in timed)
        worst = max(us for _, us in timed)
        print(f"  {family:10s} {len(timed)} candidates  "
              f"best {best:9.1f} us {entry.block}  "
              f"spread x{worst / best:.2f}")
        if on_event is not None:
            on_event(autotune.tune_event(
                "sweep", entry.fingerprint, family,
                n_candidates=len(timed),
                best_us=entry.sweep["best_us"],
                default_us=entry.sweep["default_us"],
                chip=entry.chip, dtype=entry.dtype))
    return db, grids, total


# --- audit legs --------------------------------------------------------------

def audit_sweep_accounting(tmp):
    import jax
    import jax.numpy as jnp

    from apex_tpu import monitor
    from apex_tpu.ops import autotune
    from apex_tpu.prof import compile_watch

    print("== sweep: interpret-mode grid per family, compiles accounted")
    compile_watch.install()
    events = []
    before = compile_watch.global_counters()["autotune_compiles"]
    db, grids, total = run_sweep(on_event=events.append)
    after = compile_watch.global_counters()["autotune_compiles"]
    assert after - before == total, (
        f"autotune_scope accounted {after - before} compiles for a "
        f"{total}-candidate sweep — sweep compiles must be accounted "
        f"exactly, never mistaken for steady-state retraces")
    print(f"  autotune_scope: exactly {total} sweep compiles accounted")

    assert set(db.families()) == set(autotune.FAMILIES), db.families()

    # (d) measurable spread on at least one family — the optimizer
    # grid's 512-vs-64 block is an 8x interpret grid-step difference
    spreads = {fam: max(us for _, us in t) / min(us for _, us in t)
               for fam, t in grids.items()}
    best_fam = max(spreads, key=spreads.get)
    assert spreads[best_fam] >= 1.05, (
        f"no family shows a measurable candidate spread: {spreads}")
    print(f"  measurable win: {best_fam} spread x{spreads[best_fam]:.2f}"
          f" across its grid")

    # steady state: consulting the fresh DB at trace time is a table
    # lookup — n_autotune_compiles must NOT move
    n, hdim = 256, 192
    x = jnp.ones((n, hdim), jnp.float32)
    w = jnp.ones((hdim,), jnp.float32)
    b = jnp.zeros((hdim,), jnp.float32)
    with autotune.use_db(db):
        autotune.reset_counters()
        before = compile_watch.global_counters()["autotune_compiles"]

        @jax.jit
        def step(x_, w_, b_):
            from apex_tpu import ops
            return ops.fused_layer_norm_affine(x_, w_, b_).sum()

        jax.block_until_ready(step(x, w, b))
        after = compile_watch.global_counters()["autotune_compiles"]
        hits = autotune.counters()["hits"]
    assert after == before, (
        f"steady-state consult cost {after - before} autotune compiles; "
        f"expected 0")
    assert hits >= 1, "tuned dispatch did not register a DB hit"
    fp = autotune.fingerprint("layer_norm", (n, hdim), jnp.float32)
    assert any(f == fp and hit for f, hit in autotune.recent_consults()), \
        autotune.recent_consults()
    print(f"  steady-state: n_autotune_compiles +0, exact-key hit {fp}")

    # (g) the tune-event stream validates on the roofline channel
    events.append(autotune.tune_event("hit", fp, "layer_norm",
                                      block_rows=db.lookup(fp).block
                                      .get("block_rows")))
    events_path = os.path.join(tmp, "tune.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], roofline_sink=monitor.JSONLSink(events_path))
    for ev in events:
        logger.record_roofline(ev)
    logger.close()
    _run_schema(events_path)
    print(f"  tune events validate (--kind roofline): {events_path}")
    return db


def audit_db_roundtrip(tmp, db):
    import jax.numpy as jnp

    from apex_tpu.ops import autotune

    print("== DB round-trip, exact-key-only matching, stale refusal")
    path = os.path.join(tmp, "tuning_db.json")
    db.save(path)
    db2 = autotune.TuningDB.load(path)
    assert set(db2.entries) == set(db.entries)

    dims = (256, 192)
    fp = autotune.fingerprint("layer_norm", dims, jnp.float32)
    assert db2.lookup(fp) is not None, f"exact key {fp} missed after reload"
    with autotune.use_db(db2):
        hit = autotune.lookup_blocks("layer_norm", dims, jnp.float32)
        assert hit == db2.lookup(fp).block, hit
        near = autotune.lookup_blocks("layer_norm", (dims[0] + 1, dims[1]),
                                      jnp.float32)
        assert near is None, (
            f"nearest-miss (257, 192) matched {near} — consultation "
            f"must be exact-key only")
    print(f"  write -> reload -> exact-key hit {fp}; (257,192) miss")

    # seeded stale entry: same key, mutated recorded dims
    raw = json.load(open(path))
    key = fp
    raw["entries"][key]["dims"] = [dims[0], dims[1] + 1]
    stale_path = os.path.join(tmp, "tuning_db_stale.json")
    json.dump(raw, open(stale_path, "w"))
    try:
        autotune.TuningDB.load(stale_path)
    except autotune.StaleTuningEntry as e:
        assert key in str(e) and "stale" in str(e).lower(), e
        print(f"  seeded stale entry refused loudly: "
              f"{str(e).split(':')[2][:60].strip()}...")
    else:
        raise AssertionError(
            "stale tuning entry (mismatched shape fingerprint) was "
            "silently accepted")


def audit_committed_db():
    import jax.numpy as jnp

    from apex_tpu.ops import autotune

    print("== committed DB serves trace-time hits for every family")
    db = autotune.TuningDB.load(_DB_PATH)   # raises StaleTuningEntry if bad
    assert len(db) >= len(autotune.FAMILIES), db.stats()
    missing = set(autotune.FAMILIES) - set(db.families())
    assert not missing, f"committed DB lacks families: {missing}"

    specs = sweep_specs()
    with autotune.use_db(db):
        autotune.reset_counters()
        for family, (dims, dtype, _) in specs.items():
            blocks = autotune.lookup_blocks(family, dims, dtype)
            assert blocks, (
                f"committed DB misses its own sweep shape: "
                f"{autotune.fingerprint(family, dims, dtype)}")
        hits = autotune.counters()["hits"]
    assert hits == len(specs), autotune.counters()
    print(f"  {len(db)} entries, families {db.families()}, "
          f"{hits}/{len(specs)} exact-key hits on the sweep shapes")
    return db


def audit_tune_report(tmp, db):
    from apex_tpu import monitor
    from apex_tpu.prof import roofline, xplane
    from apex_tpu.ops import autotune

    print("== tune_report joins worst_gaps off the BERT-layer fixture")
    os.environ["APEX_TPU_XPLANE_PURE"] = "1"
    tp = xplane.parse_trace(os.path.join(_FIXTURES,
                                         "bert_layer.xplane.pb"))
    rep = roofline.roofline_report(profile=tp, device_kind="TPU v5 lite")
    gaps = rep.worst_gaps(5)
    report = autotune.tune_report(db=db, worst_gaps=gaps)
    assert report["n_candidates"] == len(gaps)

    bwd = [c for c in report["candidates"] if c["op"] == "custom-call.202"]
    assert bwd, [c["op"] for c in report["candidates"]]
    c = bwd[0]
    assert c["family"] == "attention", c
    assert 540.0 <= c["measured_us"] <= 560.0, c
    assert 420.0 <= c["attainable_us"] <= 450.0, c
    assert c["covered"], (
        "the ~549-vs-436 us fused-backward attention candidate is NOT "
        f"covered by a committed tuning entry: {c}")
    assert c["db_entries"], c
    print(f"  fused-backward candidate covered: "
          f"{c['measured_us']:.0f} us measured vs "
          f"{c['attainable_us']:.0f} us floor -> entries "
          f"{c['db_entries']}")
    assert "attention" in report["tuned_families"]

    # the joined report rides the roofline channel as tune events
    events_path = os.path.join(tmp, "tune_report.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], roofline_sink=monitor.JSONLSink(events_path))
    for cand in report["candidates"]:
        logger.record_roofline(autotune.tune_event(
            "hit" if cand["covered"] else "miss",
            cand["fingerprint"] or "", cand["family"] or "unknown",
            gap_us=cand["gap_us"]))
    logger.close()
    _run_schema(events_path)
    print(f"  joined report events validate: {events_path}")


def main_cpu8():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from apex_tpu import _compat
    _compat.request_cpu_devices(8)

    with tempfile.TemporaryDirectory() as tmp:
        db = audit_sweep_accounting(tmp)
        audit_db_roundtrip(tmp, db)
        committed = audit_committed_db()
        audit_tune_report(tmp, committed)
    print("\nkernel_tune audit ok")


def main_update_db():
    from apex_tpu.ops import autotune
    from apex_tpu.prof import compile_watch

    compile_watch.install()
    print(f"== sweeping {len(autotune.FAMILIES)} families "
          f"(chip={autotune.chip_kind()})")
    db, _, total = run_sweep()
    # merge over any existing entries for OTHER keys (e.g. another
    # chip's artifacts) — a sweep only overwrites what it re-measured
    try:
        existing = autotune.TuningDB.load(_DB_PATH)
    except autotune.StaleTuningEntry as e:
        print(f"  discarding stale DB: {e}")
        existing = autotune.TuningDB()
    for key, entry in db.entries.items():
        existing.entries[key] = entry
    existing.save(_DB_PATH)
    n_auto = compile_watch.global_counters()["autotune_compiles"]
    print(f"  {total} candidates timed ({n_auto} accounted compiles) -> "
          f"{len(existing)} entries in {_DB_PATH}")


if __name__ == "__main__":
    if "--interpret" in sys.argv:
        os.environ["APEX_TPU_FORCE_INTERPRET"] = "1"
    if "--update-db" in sys.argv:
        main_update_db()
    elif "--cpu8" in sys.argv:
        main_cpu8()
    else:
        print(__doc__)
        sys.exit(2)
