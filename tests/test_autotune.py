"""Kernel autotuner — tuning-DB semantics + dispatch-seam contracts.

The ISSUE-18 claims, CPU/interpret-testable:

- **fingerprint stability**: the ``family|dims|dtype|chip`` key is
  derived from the dtype *object*'s canonical name and python ints —
  every spelling of the same logical shape (np dtype, jnp dtype,
  string, weak type) produces the identical key across jax versions;
- **exact-key only**: a nearest miss (one row off, other dtype) never
  matches — consultation is a dict lookup, not a similarity search;
- **stale refusal**: an entry whose recorded identity no longer
  re-fingerprints to its key raises ``StaleTuningEntry`` at load;
- **off-mode bitwise**: ``APEX_TPU_AUTOTUNE=off`` produces outputs
  bitwise-identical to the DB-miss path (the pre-tuner trajectory);
- **tuned-vs-default bitwise per family** (interpret mode): row-block
  and block_q retilings change the schedule, never the math — the
  block-invariant representative of each family matches bitwise;
- **satellite-2 refusal**: a tuned/explicit optimizer block that does
  not divide the BUFFER_MULTIPLE-padded arena buffer warns naming the
  offending fingerprint + the fallback taken, and still computes the
  default-block result;
- **APX104 negative twin**: a DB-satisfied shape signature stays at
  info severity (no escalation), with the fix-it naming the DB.
"""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import lint, ops, prof
from apex_tpu.ops import autotune
from apex_tpu.ops import _dispatch


@pytest.fixture(autouse=True)
def _reset_autotune_state():
    autotune.reset_counters()
    yield
    autotune.set_db(None)
    autotune.reset_counters()


def _entry(family, dims, block, dtype="float32", **kw):
    return autotune.TuningEntry(family=family, dims=tuple(dims),
                                dtype=dtype, chip=autotune.chip_kind(),
                                block=dict(block), **kw)


def _db(*entries):
    return autotune.TuningDB({e.fingerprint: e for e in entries})


# --- fingerprint semantics ---------------------------------------------------

class TestFingerprint:
    def test_stable_across_dtype_spellings(self):
        want = autotune.fingerprint("layer_norm", (48, 96),
                                    np.float32, chip="cpu")
        for spelling in (jnp.float32, np.dtype("float32"), "float32",
                         np.float32, jnp.zeros((1,), jnp.float32).dtype):
            assert autotune.fingerprint(
                "layer_norm", (48, 96), spelling, chip="cpu") == want
        assert want == "layer_norm|48x96|float32|cpu"

    def test_bfloat16_and_int_dims(self):
        fp = autotune.fingerprint("xentropy", (np.int64(8), 30522),
                                  jnp.bfloat16, chip="cpu")
        assert fp == "xentropy|8x30522|bfloat16|cpu"

    def test_unknown_family_refused(self):
        with pytest.raises(ValueError, match="unknown kernel family"):
            autotune.fingerprint("conv", (8, 8), jnp.float32)

    def test_chip_key_is_cpu_off_tpu(self):
        assert autotune.chip_kind() == "cpu"


# --- DB load/save/lookup -----------------------------------------------------

class TestTuningDB:
    def test_roundtrip_and_exact_key_hit(self, tmp_path):
        e = _entry("layer_norm", (256, 192), {"block_rows": 64})
        db = _db(e)
        path = str(tmp_path / "db.json")
        db.save(path)
        db2 = autotune.TuningDB.load(path)
        assert db2.lookup(e.fingerprint).block == {"block_rows": 64}
        with autotune.use_db(db2):
            assert autotune.lookup_blocks(
                "layer_norm", (256, 192), jnp.float32) == \
                {"block_rows": 64}
            assert autotune.counters()["hits"] == 1

    def test_nearest_miss_does_not_match(self):
        e = _entry("layer_norm", (256, 192), {"block_rows": 64})
        with autotune.use_db(_db(e)):
            for dims, dtype in (((257, 192), jnp.float32),
                                ((256, 191), jnp.float32),
                                ((256, 192), jnp.bfloat16)):
                assert autotune.lookup_blocks(
                    "layer_norm", dims, dtype) is None
            assert autotune.lookup_blocks(
                "xentropy", (256, 192), jnp.float32) is None
        assert autotune.counters()["hits"] == 0

    def test_stale_entry_refused_loudly(self, tmp_path):
        e = _entry("mlp", (128, 96, 64), {"block_rows": 32})
        path = str(tmp_path / "db.json")
        _db(e).save(path)
        raw = json.load(open(path))
        raw["entries"][e.fingerprint]["dims"] = [128, 96, 65]
        json.dump(raw, open(path, "w"))
        with pytest.raises(autotune.StaleTuningEntry) as exc:
            autotune.TuningDB.load(path)
        assert e.fingerprint in str(exc.value)
        assert "kernel_tune" in str(exc.value)

    def test_malformed_entry_refused(self, tmp_path):
        path = str(tmp_path / "db.json")
        json.dump({"version": 1, "entries": {"k": {"family": "mlp"}}},
                  open(path, "w"))
        with pytest.raises(autotune.StaleTuningEntry):
            autotune.TuningDB.load(path)

    def test_missing_file_is_empty_db(self, tmp_path):
        db = autotune.TuningDB.load(str(tmp_path / "absent.json"))
        assert len(db) == 0

    def test_committed_db_loads_with_all_families(self):
        db = autotune.TuningDB.load(autotune.default_db_path())
        assert set(autotune.FAMILIES) <= set(db.families())
        for e in db.entries.values():
            assert e.sweep.get("n_candidates", 0) >= 2
            assert e.sweep.get("best_us", 0) > 0

    def test_off_mode_skips_consult(self, monkeypatch):
        e = _entry("layer_norm", (256, 192), {"block_rows": 64})
        monkeypatch.setenv("APEX_TPU_AUTOTUNE", "off")
        with autotune.use_db(_db(e)):
            assert autotune.lookup_blocks(
                "layer_norm", (256, 192), jnp.float32) is None
        assert autotune.counters() == {"hits": 0, "misses": 0}

    def test_bad_mode_refused(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_AUTOTUNE", "fast")
        with pytest.raises(ValueError, match="refusing to guess"):
            autotune.mode()

    def test_illegal_tuned_value_warns_and_falls_back(self):
        e = _entry("layer_norm", (256, 192), {"block_rows": 40})
        with autotune.use_db(_db(e)):
            with pytest.warns(RuntimeWarning,
                              match="layer_norm|256x192"):
                got = autotune.tuned_rows("layer_norm", (256, 192),
                                          jnp.float32)
        assert got is None


# --- off-mode bitwise + tuned-vs-default bitwise per family ------------------

class TestBitwiseNumerics:
    def test_off_trajectory_bitwise_identical_to_miss(self, monkeypatch):
        x = jnp.asarray(np.random.RandomState(0).randn(48, 96),
                        jnp.float32)
        w = jnp.ones((96,), jnp.float32)
        b = jnp.zeros((96,), jnp.float32)
        monkeypatch.setenv("APEX_TPU_AUTOTUNE", "off")
        y_off = np.asarray(ops.fused_layer_norm_affine(x, w, b))
        monkeypatch.setenv("APEX_TPU_AUTOTUNE", "db")
        y_db = np.asarray(ops.fused_layer_norm_affine(x, w, b))
        np.testing.assert_array_equal(y_off, y_db)

    def test_layer_norm_tuned_vs_default_bitwise(self):
        from apex_tpu.ops import layer_norm as ln
        x = jnp.asarray(np.random.RandomState(1).randn(96, 80),
                        jnp.float32)
        w = jnp.asarray(np.random.RandomState(2).rand(80), jnp.float32)
        b = jnp.asarray(np.random.RandomState(3).rand(80), jnp.float32)
        default = np.asarray(ln._ln_forward(x, w, b, 1e-5))
        for r in (16, 32, 96):
            tuned = np.asarray(ln._ln_forward(x, w, b, 1e-5,
                                              block_rows=r))
            np.testing.assert_array_equal(default, tuned)

    def test_xentropy_tuned_vs_default_bitwise(self):
        from apex_tpu.ops import xentropy as xe
        x = jnp.asarray(np.random.RandomState(4).randn(64, 300),
                        jnp.float32)
        lab = jnp.asarray(np.random.RandomState(5).randint(0, 300, 64),
                          jnp.int32)
        loss_d, lse_d = xe._fwd_call(x, lab, 0.1)
        for r in (16, 32, 64):
            loss_t, lse_t = xe._fwd_call(x, lab, 0.1, block_rows=r)
            np.testing.assert_array_equal(np.asarray(loss_d),
                                          np.asarray(loss_t))
            np.testing.assert_array_equal(np.asarray(lse_d),
                                          np.asarray(lse_t))

    def test_mlp_tuned_vs_default_bitwise(self):
        from apex_tpu.ops import mlp as mlp_mod
        rng = np.random.RandomState(6)
        x = jnp.asarray(rng.randn(64, 48), jnp.float32)
        ws = (jnp.asarray(rng.randn(48, 64) * 0.1, jnp.float32),
              jnp.asarray(rng.randn(64, 32) * 0.1, jnp.float32))
        bs = (jnp.zeros((64,), jnp.float32),
              jnp.zeros((32,), jnp.float32))
        default = np.asarray(mlp_mod._fused_mlp_fwd_impl(
            x, ws, bs, "relu"))
        for r in (16, 32, 64):
            tuned = np.asarray(mlp_mod._fused_mlp_fwd_impl(
                x, ws, bs, "relu", block_rows=r))
            np.testing.assert_array_equal(default, tuned)

    def test_optimizer_tuned_vs_default_bitwise(self):
        from apex_tpu.ops import multi_tensor as mt
        buf = jnp.asarray(np.random.RandomState(7).randn(512 * 128),
                          jnp.float32)

        def scale(block_rows):
            out, flag = _dispatch.launch(
                mt._scale_kernel, [buf],
                outs=[("block", jnp.float32), ("scalar", jnp.float32)],
                scalars=[1.7], block_rows=block_rows)
            return np.asarray(out), bool(flag[0, 0] == 0.0)

        out_d, ok_d = scale(None)
        for r in (64, 128, 256):
            out_t, ok_t = scale(r)
            np.testing.assert_array_equal(out_d, out_t)
            assert ok_d == ok_t

    def test_attention_tuned_vs_default_bitwise(self):
        # the committed-DB pattern: the tuned entry's blocks realize to
        # the same blocks the default dispatch clamps to at this shape
        # (1024 -> 256), so a DB hit is the identical program — tuned
        # dispatch adds nothing numerically
        rng = np.random.RandomState(8)
        q = jnp.asarray(rng.randn(1, 256, 2, 64), jnp.float32)
        k = jnp.asarray(rng.randn(1, 256, 2, 64), jnp.float32)
        v = jnp.asarray(rng.randn(1, 256, 2, 64), jnp.float32)
        base = np.asarray(ops.flash_attention(q, k, v, block_q=256,
                                              block_k=256))
        e = _entry("attention", (1, 256, 256, 2, 64),
                   {"block_q": 256, "block_k": 256})
        with autotune.use_db(_db(e)):
            tuned = np.asarray(ops.flash_attention(q, k, v))
            assert autotune.counters()["hits"] >= 1
        np.testing.assert_array_equal(base, tuned)
        # a genuine block_q retile changes XLA:CPU's gemm row
        # partitioning (reassociated fp32 sums on the 8-device test
        # backend, ~1e-7) — equal to fp32 resolution, not bitwise there
        for bq in (64, 128):
            o = np.asarray(ops.flash_attention(q, k, v, block_q=bq,
                                               block_k=256))
            np.testing.assert_allclose(base, o, rtol=0, atol=1e-6)

    def test_attention_tuned_via_db_matches_explicit(self):
        rng = np.random.RandomState(9)
        q = jnp.asarray(rng.randn(1, 128, 2, 64), jnp.float32)
        e = _entry("attention", (1, 128, 128, 2, 64),
                   {"block_q": 64, "block_k": 128})
        explicit = np.asarray(ops.flash_attention(q, q, q, block_q=64,
                                                  block_k=128))
        with autotune.use_db(_db(e)):
            tuned = np.asarray(ops.flash_attention(q, q, q))
            assert autotune.counters()["hits"] >= 1
        np.testing.assert_array_equal(explicit, tuned)


# --- satellite 2: the launch-time refusal ------------------------------------

class TestBlockRefusal:
    def test_nondividing_tuned_block_warns_with_fingerprint(self):
        from apex_tpu.ops import multi_tensor as mt
        n = 512 * 128          # BUFFER_MULTIPLE-padded, 512 rows
        buf = jnp.ones((n,), jnp.float32)
        # 96 is on the sublane grid (passes tuned_rows validation) but
        # does not divide the 512-row buffer — the satellite-2 shape
        e = _entry("optimizer", (n,), {"block_rows": 96})
        fp = e.fingerprint
        with autotune.use_db(_db(e)):
            with pytest.warns(RuntimeWarning) as rec:
                out, flag = _dispatch.launch(
                    mt._scale_kernel, [buf],
                    outs=[("block", jnp.float32),
                          ("scalar", jnp.float32)],
                    scalars=[2.0])
        msgs = [str(w.message) for w in rec]
        assert any(fp in m and "falling back" in m
                   and f"BLOCK_ROWS={_dispatch.BLOCK_ROWS}" in m
                   for m in msgs), msgs
        np.testing.assert_array_equal(np.asarray(out),
                                      np.full((n,), 2.0, np.float32))

    def test_explicit_nondividing_block_warns_and_falls_back(self):
        from apex_tpu.ops import multi_tensor as mt
        buf = jnp.ones((512 * 128,), jnp.float32)
        with pytest.warns(RuntimeWarning, match="explicit block_rows"):
            out, _ = _dispatch.launch(
                mt._scale_kernel, [buf],
                outs=[("block", jnp.float32), ("scalar", jnp.float32)],
                scalars=[3.0], block_rows=384)
        assert float(out[0]) == 3.0

    def test_as_rows_refusal_names_the_contract(self):
        with pytest.raises(AssertionError) as exc:
            _dispatch.as_rows(jnp.ones((1000,), jnp.float32))
        msg = str(exc.value)
        assert "apex_tpu.arena.flatten" in msg
        assert "BUFFER_MULTIPLE" in msg
        assert "_resolve_block_rows" in msg


# --- APX104 negative twin ----------------------------------------------------

class TestApx104TuningDB:
    def _warning_sig(self):
        """An off-grid dot big enough to escalate: >=25% waste, >=1MiB."""
        def mm(a, b):
            return a @ b

        text = prof.hlo.compiled_hlo(
            mm, jnp.zeros((9, 2048), jnp.float32),
            jnp.zeros((2048, 129), jnp.float32))
        hits = [f for f in lint.hlo_pass.tile_findings(text)
                if f.rule == "tile-padding"]
        assert hits and any(f.severity == "warning" for f in hits), hits
        warn = [f for f in hits if f.severity == "warning"][0]
        return text, warn.scope

    def test_db_satisfied_shape_does_not_escalate(self):
        text, sig = self._warning_sig()
        covered = [f for f in lint.hlo_pass.tile_findings(
                       text, tuned_shapes=[sig])
                   if f.scope == sig]
        assert covered and covered[0].severity == "info"
        assert "kernel_tuning_db" in covered[0].message

    def test_other_shapes_still_escalate(self):
        text, sig = self._warning_sig()
        still = [f for f in lint.hlo_pass.tile_findings(
                     text, tuned_shapes=["some-other-sig"])
                 if f.scope == sig]
        assert still and still[0].severity == "warning"

    def test_lint_hlo_text_passthrough(self):
        text, sig = self._warning_sig()
        findings = lint.lint_hlo_text(text, tuned_shapes=[sig])
        tp = [f for f in findings if f.rule == "tile-padding"
              and f.scope == sig]
        assert tp and tp[0].severity == "info"

    def test_apx104_fix_names_the_workflow(self):
        from apex_tpu.lint import findings as F
        rule = F.RULES["tile-padding"]
        assert rule.id == "APX104"
        assert "kernel_tune.py" in rule.fix
        assert "kernel_tuning_db.json" in rule.fix

    def test_tuned_lint_shapes_from_entries(self):
        e = _entry("mlp", (64, 48, 32), {"block_rows": 32},
                   lint_sigs=("f32[9,2048] x f32[2048,129]",))
        assert autotune.tuned_lint_shapes(_db(e)) == \
            ["f32[9,2048] x f32[2048,129]"]


# --- tune_report join --------------------------------------------------------

class TestTuneReport:
    def test_family_join_and_coverage(self):
        e = _entry("attention", (1, 256, 256, 2, 64),
                   {"block_q": 256, "block_k": 256},
                   sweep={"best_us": 400.0, "default_us": 520.0})
        gaps = [{"fingerprint": "attention|custom-call|bwd|f32[...]",
                 "family": "attention", "op": "custom-call.202",
                 "measured_us": 549.0, "attainable_us": 436.0,
                 "gap_us": 113.0},
                {"fingerprint": "mlp|fusion|x|f32[...]",
                 "family": "mlp", "op": "fusion.3",
                 "measured_us": 100.0, "attainable_us": 90.0,
                 "gap_us": 10.0}]
        rep = autotune.tune_report(db=_db(e), worst_gaps=gaps)
        assert rep["n_candidates"] == 2 and rep["n_covered"] == 1
        attn = next(c for c in rep["candidates"]
                    if c["op"] == "custom-call.202")
        assert attn["covered"] and attn["db_entries"] == [e.fingerprint]
        assert attn["predicted_closure_us"] == 120.0
        assert rep["uncovered_families"] == ["mlp"]

    def test_events_round_trip_monitor_channel(self, tmp_path):
        from apex_tpu import monitor
        path = str(tmp_path / "tune.jsonl")
        logger = monitor.MetricsLogger(
            sinks=[], roofline_sink=monitor.JSONLSink(path))
        logger.record_roofline(autotune.tune_event(
            "sweep", "layer_norm|256x192|float32|cpu", "layer_norm",
            best_us=70.0, default_us=90.0, n_candidates=5))
        logger.record_roofline(autotune.tune_event(
            "refused", "optimizer|65536|float32|cpu", "optimizer"))
        logger.close()
        recs = [json.loads(l) for l in open(path)]
        assert [r["kind"] for r in recs] == ["tune", "tune"]
        assert recs[0]["action"] == "sweep"
        from apex_tpu.monitor.logger import CHANNELS
        roof = next(c for c in CHANNELS if c.name == "roofline")
        assert "tune" in roof.kinds
