#!/usr/bin/env python
"""pod_audit — the asserting CI audit of the pod observatory
(run by ``run_tier1.sh --smoke``; exit status is the verdict).

Four asserted legs:

(a) **deterministic skew blame**: a synthetic 4-rank pod (per-rank
    clock offsets of ±seconds, rank 2 seeded 60 ms late inside a
    ``data/load`` span before every collective) merges into one
    :class:`apex_tpu.trace.PodTimeline` whose clock fit recovers the
    injected offsets to sub-microsecond residual, and EVERY
    collective's blame lands on exactly ``(rank 2, "data/load")`` with
    the injected 60 ms skew / 5 ms wire split exact. The critical path
    chains those (wait → wire) segments, a rank with no collective
    spans merges ``aligned=False`` at offset 0 instead of silently
    pretending, and the emitted podview events match the committed
    ``tests/fixtures/podview_pod_audit.jsonl`` fixture — which itself
    must validate under ``check_metrics_schema.py --kind podview``.

(b) **goodput split closure**: an instrumented loop with collective
    spans joins a pod-measured 12 ms skew per step
    (:meth:`GoodputLedger.note_pod_skew`); the ``comm_skew`` bucket
    gets exactly the joined milliseconds OUT of ``comm_wire`` (never
    invented), the bucket sum still closes over wall time within 5%,
    and an oversized skew claim is clamped to the measured collective
    time. The stream validates under the updated ``--kind goodput``.

(c) **multiprocess merge**: 4 REAL processes run traced steps whose
    per-step collective span blocks on a shared-filesystem barrier —
    the last arriver's write releases everyone, modeling exactly the
    simultaneous-exit semantics the clock-alignment contract is built
    on (XLA:CPU cannot execute cross-process collectives; the real
    jax.distributed rendezvous path is pinned by
    tests/test_multiproc_launch.py). Rank 2 sleeps 80 ms in
    ``data/load`` before each barrier. The parent merges the four
    per-rank span streams — four genuinely unrelated ``perf_counter``
    origins — and every steady-state collective must blame
    ``(rank 2, "data/load")`` with > 40 ms skew.

(d) **plan-vs-measured comm drift**: linkbench calibrates the factored
    dp2x4 CPU mesh into a MEASURED MeshModel; ``plan_comm`` derives
    the 3-hop fp32 schedule; :func:`apex_tpu.monitor.measure_hops`
    times each hop for real and :func:`compare` must agree with the
    plan's ``hop_seconds`` within a stated 25x ratio band (α–β models
    are order-of-magnitude instruments and XLA:CPU emulation is noisy
    — the band pins the *pipeline*; on-chip runs tighten it). The
    negative twin deliberately stales the model (bytes/s ÷ 10⁴) and
    the drift flag MUST fire with stable ``comm_drift|op|axis/link``
    fingerprints and advice naming ``scripts/link_probe.py``. The
    drift stream validates under ``--kind podview``.

Usage: JAX_PLATFORMS=cpu python scripts/pod_audit.py --cpu8
"""

import json
import os
import subprocess
import sys
import textwrap
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(_REPO, "tests", "fixtures",
                        "podview_pod_audit.jsonl")
#: pinned wall_time for the committed fixture (2026-08-06 00:00 UTC) —
#: the synthetic leg is deterministic, so fresh events must EQUAL the
#: committed ones when stamped with the same clock
_FIXTURE_WALL = 1785974400.0


def _run_schema(path: str, kind: str) -> None:
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts",
                                      "check_metrics_schema.py"),
         "--kind", kind, path],
        capture_output=True, text=True)
    assert r.returncode == 0, (
        f"schema validation failed for {path}:\n{r.stdout}{r.stderr}")


# --- leg (a): deterministic skew blame ----------------------------------------

#: injected truth of the synthetic pod: rank 2 is 60 ms late into
#: every collective, parked in data/load; the wire itself takes 5 ms
_SLOW_RANK, _BLAMED_SPAN = 2, "data/load"
_FAST_MS, _SLOW_MS, _WIRE_MS = 5.0, 65.0, 5.0
_OFFSETS = {0: 0.0, 1: 1234.5, 2: -987.25, 3: 41.75}


def synthetic_pod_events(n_steps: int = 3):
    """The synthetic 4-rank pod's ``kind="span"`` events, each rank on
    its own clock (local = pod − offset); plus one extra rank 4 that
    shares NO collective (the unalignable-rank edge case)."""
    events = []
    for step in range(n_steps):
        base = 1000.0 + step * 100.0        # pod-clock step start
        exit_ms = base + _SLOW_MS + _WIRE_MS
        for r, off in _OFFSETS.items():
            work = _SLOW_MS if r == _SLOW_RANK else _FAST_MS
            entry = base + work
            events.append({"kind": "span", "name": _BLAMED_SPAN,
                           "span_kind": "span", "step": step, "rank": r,
                           "t_ms": base - off, "dur_ms": work,
                           "depth": 1})
            events.append({"kind": "span", "name": "grad/allreduce",
                           "span_kind": "collective", "step": step,
                           "rank": r, "t_ms": entry - off,
                           "dur_ms": exit_ms - entry, "depth": 1})
        events.append({"kind": "span", "name": "data/load",
                       "span_kind": "span", "step": step, "rank": 4,
                       "t_ms": base - 5e6, "dur_ms": _FAST_MS,
                       "depth": 1})
    return events


def audit_pod_blame(tmp: str) -> None:
    from apex_tpu import monitor, trace

    print("== pod merge + collective-skew blame (synthetic 4-rank pod)")
    n_steps = 3
    pod = trace.PodTimeline.merge(synthetic_pod_events(n_steps))
    assert pod.ranks == [0, 1, 2, 3, 4], pod.ranks

    al = pod.alignment
    assert al.reference == 0, al.reference
    for r, off in _OFFSETS.items():
        c = al.clocks[r]
        assert c.aligned, f"rank {r} should have aligned"
        assert abs(c.offset_ms - off) < 1e-6, (r, c.offset_ms, off)
        assert c.residual_ms is not None and c.residual_ms < 1e-6, c
    c4 = al.clocks[4]
    assert not c4.aligned and c4.offset_ms == 0.0 \
        and c4.n_shared == 0, c4

    skews = pod.collective_skew()
    assert len(skews) == n_steps, [s.name for s in skews]
    for s in skews:
        assert s.n_ranks == 4, s
        assert s.blamed_rank == _SLOW_RANK, s
        assert s.blamed_span == _BLAMED_SPAN, s
        assert abs(s.skew_ms - (_SLOW_MS - _FAST_MS)) < 1e-6, s
        assert abs(s.wire_ms - _WIRE_MS) < 1e-6, s
    print(f"  {n_steps} collectives: blame (rank {_SLOW_RANK}, "
          f"{_BLAMED_SPAN!r}), skew {skews[0].skew_ms:.1f} ms / wire "
          f"{skews[0].wire_ms:.1f} ms, clock residual < 1e-6 ms, "
          f"rank 4 unaligned as designed")

    waits = pod.rank_step_skew()
    for step in range(n_steps):
        for r in _OFFSETS:
            want = 0.0 if r == _SLOW_RANK else _SLOW_MS - _FAST_MS
            got = waits.get((r, step), 0.0)
            assert abs(got - want) < 1e-6, (r, step, got, want)

    path = pod.critical_path(1)
    assert [seg["segment"] for seg in path] == ["wait", "wire"], path
    assert path[0]["rank"] == _SLOW_RANK \
        and path[0]["span"] == _BLAMED_SPAN, path
    print(f"  critical path (step 1): wait {path[0]['dur_ms']:.1f} ms "
          f"on (rank {path[0]['rank']}, {path[0]['span']!r}) -> wire "
          f"{path[1]['dur_ms']:.1f} ms")

    ct = pod.chrome_trace()
    names = {m["pid"]: m["args"]["name"]
             for m in ct["traceEvents"]
             if m.get("ph") == "M" and m["name"] == "process_name"}
    assert names[0] == "rank 0" and names[4] == "rank 4 (unaligned)", \
        names

    # the podview event stream: fresh events, stamped with the
    # fixture's pinned wall clock, must EQUAL the committed fixture
    # (the leg is deterministic by construction), and both validate
    events = pod.to_events(wall_time=_FIXTURE_WALL)
    events_path = os.path.join(tmp, "podview.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], podview_sink=monitor.JSONLSink(events_path))
    for ev in events:
        logger.record_podview(ev)
    logger.close()
    _run_schema(events_path, "podview")
    _run_schema(_FIXTURE, "podview")
    committed = [json.loads(l) for l in open(_FIXTURE)]
    assert committed == events, (
        "fresh podview events diverge from the committed fixture "
        "tests/fixtures/podview_pod_audit.jsonl — regenerate it via "
        "synthetic_pod_events() or fix the regression")
    print(f"  events validate (--kind podview) and match the "
          f"committed fixture ({len(committed)} records)")


# --- leg (b): goodput comm_skew/comm_wire split closure -----------------------

def _traced_steps(note_skew_ms, n_steps: int = 3):
    from apex_tpu import monitor, trace

    tracer = trace.Tracer()
    ledger = monitor.GoodputLedger(tracer, tolerance=0.05)
    with tracer:
        for i in range(n_steps):
            with trace.step(i):
                with trace.span("data/load"):
                    time.sleep(0.002)
                with trace.span("dispatch"):
                    time.sleep(0.004)
                with trace.span("grad/sync", kind="collective"):
                    time.sleep(0.020)
                ledger.note_pod_skew(note_skew_ms, step=i)
    return ledger


def audit_split_closure(tmp: str) -> None:
    from apex_tpu import monitor

    print("== goodput comm_skew/comm_wire split closure")
    ledger = _traced_steps(12.0)
    ok, worst = ledger.check_closure(tolerance=0.05)
    assert ok, f"bucket sum no longer closes after the split: {worst}"
    for rec in ledger.steps:
        b = rec.buckets
        assert abs(b["comm_skew"] - 12.0) < 1e-9, b
        assert b["comm_wire"] >= 7.0, b      # 20 ms sleep - 12 joined
        assert abs(rec.exposed_comm
                   - (b["comm_skew"] + b["comm_wire"])) < 1e-9
    print(f"  12 ms pod skew joined out of comm_wire per step; "
          f"closure worst error {worst:.2%} (<= 5%)")

    # clamp twin: a skew claim bigger than the measured collective
    # time moves ALL of comm_wire and nothing else — pod blame can
    # reclassify exposed collective time, never invent it
    clamped = _traced_steps(10_000.0)
    ok, worst = clamped.check_closure(tolerance=0.05)
    assert ok, worst
    for rec in clamped.steps:
        b = rec.buckets
        assert b["comm_wire"] == 0.0, b
        assert 15.0 <= b["comm_skew"] <= 60.0, b
        assert b["compute"] > 0.0, b         # dispatch span untouched
    print("  oversized skew claim clamped to the measured collective "
          "time (closure holds)")

    events_path = os.path.join(tmp, "goodput_split.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], goodput_sink=monitor.JSONLSink(events_path))
    for ev in ledger.to_events():
        logger.record_goodput(ev)
    logger.close()
    _run_schema(events_path, "goodput")
    print(f"  events validate (--kind goodput): {events_path}")


# --- leg (c): multiprocess merge (real clocks, real collectives) --------------

_CHILD = textwrap.dedent("""
    import json, os, sys, time

    rank = int(os.environ["RANK"])
    world = int(os.environ["WORLD_SIZE"])
    bdir = os.environ["POD_BARRIER_DIR"]

    from apex_tpu import trace

    def barrier(tag):
        # the last arriver's file releases every waiter on its next
        # poll — the simultaneous-exit semantics of a blocking
        # collective, which is all the clock-alignment fit assumes
        open(os.path.join(bdir, "%s.%d" % (tag, rank)), "w").close()
        want = tag + "."
        while sum(1 for n in os.listdir(bdir)
                  if n.startswith(want)) < world:
            time.sleep(0.001)

    barrier("start")        # de-skew process startup, outside spans
    tracer = trace.Tracer()
    with tracer:
        for i in range(4):
            with trace.step(i):
                with trace.span("data/load"):
                    time.sleep(0.080 if rank == 2 else 0.005)
                with trace.span("grad/sync", kind="collective"):
                    barrier("step%d" % i)
    with open(os.environ["POD_AUDIT_OUT"], "w") as f:
        for ev in tracer.span_events(rank):
            f.write(json.dumps(ev) + chr(10))
    print("OK rank=%d" % rank, flush=True)
""")


def audit_multiproc_merge(tmp: str) -> None:
    from apex_tpu import trace

    print("== multiprocess pod merge (4 real ranks, barrier exits)")
    n_ranks = 4
    bdir = os.path.join(tmp, "barrier")
    os.makedirs(bdir, exist_ok=True)
    procs, outs = [], []
    for rank in range(n_ranks):
        env = {**os.environ, "RANK": str(rank),
               "WORLD_SIZE": str(n_ranks), "POD_BARRIER_DIR": bdir,
               "POD_AUDIT_OUT": os.path.join(tmp, f"rank{rank}.jsonl")}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise AssertionError("multiproc barrier pod timed out:\n"
                             + "\n---\n".join(outs))
    joined = "\n---\n".join(outs)
    assert all(p.returncode == 0 for p in procs), (
        f"multiproc children failed "
        f"{[p.returncode for p in procs]}:\n{joined}")

    lines = []
    for rank in range(n_ranks):
        lines.extend(open(os.path.join(tmp, f"rank{rank}.jsonl")))
    pod = trace.PodTimeline.merge(lines)
    assert pod.ranks == list(range(n_ranks)), pod.ranks
    assert all(c.aligned for c in pod.alignment.clocks.values()), \
        pod.alignment.clocks

    # steady state only: step 0 may fold first-dispatch noise
    skews = [c for c in pod.collective_skew() if (c.step or 0) >= 1]
    assert skews, "no matched collectives past step 0"
    for c in skews:
        assert c.n_ranks == n_ranks, c
        assert c.blamed_rank == 2, (
            f"blame landed on rank {c.blamed_rank}, want the seeded "
            f"slow rank 2: {c}")
        assert c.blamed_span == "data/load", c
        assert c.skew_ms > 40.0, c           # 80 ms vs 5 ms injected
    trace_path = pod.write_chrome_trace(
        os.path.join(tmp, "pod_trace.json"))
    events_path = os.path.join(tmp, "podview_multiproc.jsonl")
    with open(events_path, "w") as f:
        for ev in pod.to_events():
            f.write(json.dumps(ev) + "\n")
    _run_schema(events_path, "podview")
    worst = max(c.skew_ms for c in skews)
    print(f"  {len(skews)} steady-state collectives across 4 real "
          f"processes all blame (rank 2, 'data/load'), worst skew "
          f"{worst:.1f} ms; merged trace {trace_path}")


# --- leg (d): plan-vs-measured comm drift -------------------------------------

#: the audit's stated agreement band — measured/predicted per hop must
#: stay within 25x either way on the calibrated-moments-ago model
#: (order-of-magnitude instrument on noisy XLA:CPU emulation; the
#: staled twin is 10,000x off, so the band separates cleanly)
_DRIFT_TOL = 25.0


def audit_comm_drift(tmp: str) -> None:
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from apex_tpu import monitor
    from apex_tpu.lint.mesh_model import MeshModel, parse_mesh_spec
    from apex_tpu.monitor import linkbench
    from apex_tpu.parallel import plan_comm

    print("== plan-vs-measured comm drift (dp2x4 CPU mesh)")
    template = parse_mesh_spec("dp2x4", n_devices=8)
    shape = tuple(a.size for a in template.axes)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(shape),
                tuple(a.name for a in template.axes))
    model, _, _ = linkbench.calibrate(mesh, template, iters=3)
    plan = plan_comm(model, grad_bytes=1 << 20, dtypes=(None,))
    assert plan.source == "measured" and len(plan.hops) == 3, \
        plan.describe()

    measured = monitor.measure_hops(plan, mesh, iters=3)
    report = monitor.compare_comm_drift(plan, measured,
                                        tolerance=_DRIFT_TOL)
    print("  " + report.table().replace("\n", "\n  "))
    assert not report.stale, (
        f"freshly calibrated model read as stale (worst drift "
        f"{report.drift_ratio:.1f}x > {_DRIFT_TOL}x):\n"
        f"{report.table()}")

    # negative twin: stale the model by 1e4 in bytes/s and the flag
    # MUST fire against the very same measurements
    stale_json = model.to_json()
    for link in stale_json["link_bytes_per_s"]:
        stale_json["link_bytes_per_s"][link] /= 1e4
    stale_model = MeshModel.from_json(stale_json)
    stale_plan = plan_comm(stale_model, grad_bytes=1 << 20,
                           dtypes=(None,))
    stale_report = monitor.compare_comm_drift(stale_plan, measured,
                                              tolerance=_DRIFT_TOL)
    assert stale_report.stale and stale_report.stale_hops(), (
        "deliberately staled model (bytes/s / 1e4) not flagged:\n"
        + stale_report.table())
    advice = stale_report.advice()
    assert advice and "scripts/link_probe.py" in advice, advice
    for h in stale_report.stale_hops():
        assert h.fingerprint == \
            f"comm_drift|{h.op}|{h.axis}/{h.link}", h.fingerprint
    print(f"  staled twin flagged: worst drift "
          f"{stale_report.drift_ratio:.0f}x, advice -> link_probe")

    events_path = os.path.join(tmp, "pod_drift.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], podview_sink=monitor.JSONLSink(events_path))
    for ev in report.to_events() + stale_report.to_events():
        logger.record_podview(ev)
    logger.close()
    _run_schema(events_path, "podview")
    print(f"  events validate (--kind podview): {events_path}")


def main_cpu8() -> None:
    import tempfile

    import jax
    jax.config.update("jax_platforms", "cpu")
    from apex_tpu import _compat
    _compat.request_cpu_devices(8)

    with tempfile.TemporaryDirectory() as tmp:
        audit_pod_blame(tmp)
        audit_split_closure(tmp)
        audit_multiproc_merge(tmp)
        audit_comm_drift(tmp)
    print("\npod audit ok")


if __name__ == "__main__":
    if "--cpu8" in sys.argv:
        main_cpu8()
    else:
        print(__doc__)
        sys.exit(2)
