"""Version bridging for the range of jax releases apex_tpu runs on.

The framework targets current jax (`jax.shard_map`, ``check_vma``,
``jax_num_cpu_devices``); CI containers and user sites may pin older
releases where the same capabilities live under experimental names
(`jax.experimental.shard_map.shard_map` with ``check_rep``, the
``--xla_force_host_platform_device_count`` XLA flag). This module
installs the forward-looking spelling on import so the rest of the
codebase is written once, against the modern API.

Imported for its side effects at the top of ``apex_tpu/__init__``.
"""

from __future__ import annotations

import jax

__all__ = ["install", "request_cpu_devices"]


def _shard_map_shim():
    """Expose ``jax.shard_map(..., check_vma=...)`` on jax releases that
    only ship ``jax.experimental.shard_map.shard_map(..., check_rep=...)``."""
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs.setdefault("check_rep", check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)

    jax.shard_map = shard_map


def _axis_size_shim():
    """Expose ``jax.lax.axis_size(name)`` on jax releases that predate it
    (an O(1) mesh-shape lookup; ``psum(1, name)`` is the portable
    equivalent and compiles to the same constant inside collectives)."""
    if hasattr(jax.lax, "axis_size"):
        return

    def axis_size(axis_name):
        try:
            from jax.core import get_axis_env  # very old spelling
            return get_axis_env().axis_size(axis_name)
        except Exception:
            return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = axis_size


def request_cpu_devices(n: int) -> None:
    """Ask for ``n`` virtual CPU devices, on whatever jax is installed.

    Newer jax has the ``jax_num_cpu_devices`` config; older releases only
    honor the XLA flag, which must land in the environment before the CPU
    backend initializes (callers must invoke this before touching
    ``jax.devices()``).
    """
    import os
    import re
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        flag = f"--xla_force_host_platform_device_count={n}"
        flags = os.environ.get("XLA_FLAGS", "")
        # replace an inherited count (e.g. a parent test process asked
        # for a different mesh) rather than silently keeping it
        flags, n_subbed = re.subn(
            r"--xla_force_host_platform_device_count=\d+", flag, flags)
        if not n_subbed:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags


def install() -> None:
    _shard_map_shim()
    _axis_size_shim()


install()
