"""apex_tpu.arena — flat parameter arena (multi-tensor-apply substrate).

See SURVEY.md §2.3/§2.10: the reference marshals tensor lists into batched
CUDA launches; apex_tpu lays parameters out flat per dtype so one Pallas
kernel covers the whole model. Layout math runs in native C++ (csrc/) with a
Python fallback.
"""

from apex_tpu.arena.arena import (
    ArenaSpec,
    DEFAULT_ALIGNMENT,
    bucket_ids,
    flatten,
    plan,
    segment_ids,
    segment_ids_device,
    shard_pad,
    unflatten,
    valid_mask,
    zeros,
)
from apex_tpu.arena.native import native_available

__all__ = [
    "ArenaSpec", "DEFAULT_ALIGNMENT", "bucket_ids", "flatten", "plan",
    "segment_ids", "segment_ids_device", "shard_pad", "unflatten", "valid_mask", "zeros",
    "native_available",
]
