"""Pod observatory: cross-rank timeline merge + comm drift.

The ISSUE-16 contract: per-rank clock offsets are recovered exactly
from shared collective exits (alternating least squares, reference
gauge), the clock-alignment edge cases hold (a rank missing the
collectives merges unaligned rather than silently wrong, a single-rank
merge is the degenerate identity, monotonic crystal drift is recovered
with ``fit_drift=True``, out-of-order span arrival matches the same
keys), collective skew splits into wait-for-laggard vs wire with blame
on the correct (rank, span), the per-(rank, step) skew joins back into
the goodput ledger's ``comm_skew``/``comm_wire`` split with closure
intact, the merged Chrome trace carries per-rank process metadata,
plan-vs-measured comm drift flags a stale link model with a stable
fingerprint, and the podview event stream (including the committed
pod_audit fixture) validates against ``--kind podview``.
"""

import json
import os
import random
import time

import pytest

from apex_tpu import monitor
from apex_tpu.monitor import comm_drift
from apex_tpu.monitor.goodput import GoodputLedger
from apex_tpu.parallel.hierarchy import CommPlan, Hop
from apex_tpu.trace import podview
from apex_tpu.trace.spans import SpanEvent, StepTrace

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_FIXTURE = os.path.join(_REPO_ROOT, "tests", "fixtures",
                        "podview_pod_audit.jsonl")


def _schema():
    from scripts.check_metrics_schema import check_podview_lines
    return check_podview_lines


def _span(name, kind, step, rank, t_ms, dur_ms, depth=0):
    return {"kind": "span", "name": name, "span_kind": kind,
            "step": step, "rank": rank, "t_ms": t_ms, "dur_ms": dur_ms,
            "depth": depth}


def _pod_events(offsets, n_steps=2, *, slow_rank=None, slow_ms=40.0,
                drift=None):
    """Synthetic pod: each step runs ``data/load`` then a
    ``grad/allreduce`` collective entered right after. ``offsets[r]``
    shifts rank r's local clock (local = true − offset, so the fit
    recovers +offset); ``slow_rank`` loads ``slow_ms`` instead of 5 ms;
    ``drift[r]`` scales rank r's local clock rate."""
    events = []
    for rank, off in offsets.items():
        def local(t_true):
            t = t_true - off
            if drift and rank in drift:
                t = t * (1.0 + drift[rank])
            return t
        for step in range(n_steps):
            base = 1000.0 * step
            load = slow_ms if rank == slow_rank else 5.0
            events.append(_span("data/load", "span", step, rank,
                                local(base), load, depth=1))
            entry = base + load
            # everyone exits when the last arriver's wire time is done
            exit_true = base + max(slow_ms if slow_rank is not None
                                   else 5.0, 5.0) + 10.0
            events.append(_span("grad/allreduce", "collective", step,
                                rank, local(entry),
                                local(exit_true) - local(entry)))
    return events


# --- clock alignment ---------------------------------------------------------

class TestClockAlignment:
    def test_offsets_recovered_exactly(self):
        offsets = {0: 0.0, 1: 1234.5, 2: -987.25}
        pod = podview.PodTimeline.merge(_pod_events(offsets, n_steps=3))
        assert pod.alignment.reference == 0
        for r, off in offsets.items():
            clock = pod.alignment.clocks[r]
            assert clock.aligned
            assert clock.offset_ms == pytest.approx(off, abs=1e-6)
            assert clock.residual_ms == pytest.approx(0.0, abs=1e-6)
            assert clock.n_shared == 3

    def test_rank_missing_collectives_merges_unaligned(self):
        """A rank whose stream has spans but no shared collectives
        cannot be constrained: it stays in the merge with offset 0 and
        ``aligned=False`` — never a silently wrong clock."""
        events = _pod_events({0: 0.0, 1: 10.0})
        events.append(_span("data/load", "span", 0, 7, 5e6, 3.0))
        pod = podview.PodTimeline.merge(events)
        clock = pod.alignment.clocks[7]
        assert not clock.aligned
        assert clock.offset_ms == 0.0
        assert clock.n_shared == 0
        assert 7 in pod.ranks          # still present in the merge
        # and its pod_align event says so
        ev = [e for e in pod.alignment.to_events(wall_time=1.0)
              if e["rank"] == 7][0]
        assert ev["aligned"] is False

    def test_single_rank_degenerate_identity(self):
        """One rank alone is the reference: aligned by definition,
        identity clock, no shared collectives."""
        pod = podview.PodTimeline.merge(_pod_events({3: 55.0},
                                                    n_steps=1))
        clock = pod.alignment.clocks[3]
        assert pod.alignment.reference == 3
        assert clock.aligned and clock.offset_ms == 0.0
        assert clock.n_shared == 0
        assert pod.collective_skew() == []

    def test_monotonic_drift_recovered(self):
        """A crystal ticking 200 ppm fast over a long run: the
        offset-only fit leaves a growing residual; ``fit_drift=True``
        recovers the rate and collapses it."""
        offsets = {0: 0.0, 1: 500.0}
        drift = {1: 2e-4}
        events = _pod_events(offsets, n_steps=40, drift=drift)
        rigid = podview.PodTimeline.merge(events)
        fitted = podview.PodTimeline.merge(events, fit_drift=True)
        r_rigid = rigid.alignment.clocks[1].residual_ms
        r_fit = fitted.alignment.clocks[1].residual_ms
        assert r_fit < r_rigid / 10
        assert r_fit == pytest.approx(0.0, abs=1e-3)
        # drift is relative to the reference: local = true·(1+d), so
        # aligning back needs ≈ −d
        assert fitted.alignment.clocks[1].drift == \
            pytest.approx(-2e-4, rel=0.05)

    def test_out_of_order_arrival_same_match_keys(self):
        """A late-flushed JSONL segment delivers spans out of order;
        occurrence indices come from the sorted local-time order, so
        the merge is permutation-invariant."""
        events = _pod_events({0: 0.0, 1: 77.0, 2: -13.0}, n_steps=3,
                             slow_rank=2)
        shuffled = list(events)
        random.Random(16).shuffle(shuffled)
        a = podview.PodTimeline.merge(events)
        b = podview.PodTimeline.merge(shuffled)
        for r in a.alignment.clocks:
            assert b.alignment.clocks[r].offset_ms == \
                pytest.approx(a.alignment.clocks[r].offset_ms, abs=1e-9)
        sa = [(c.step, c.name, c.occurrence, c.skew_ms, c.blamed_rank)
              for c in a.collective_skew()]
        sb = [(c.step, c.name, c.occurrence, c.skew_ms, c.blamed_rank)
              for c in b.collective_skew()]
        assert sa == sb

    def test_torn_jsonl_line_skipped(self):
        lines = [json.dumps(e) for e in _pod_events({0: 0.0, 1: 5.0})]
        lines.insert(1, '{"kind": "span", "name": "torn')
        timelines = podview.load_span_events(lines)
        assert set(timelines) == {0, 1}


# --- skew blame --------------------------------------------------------------

class TestSkewBlame:
    def test_blame_lands_on_laggard_and_its_span(self):
        pod = podview.PodTimeline.merge(
            _pod_events({0: 0.0, 1: 300.0, 2: -50.0}, n_steps=2,
                        slow_rank=1, slow_ms=45.0))
        skews = pod.collective_skew()
        assert len(skews) == 2
        for c in skews:
            assert c.blamed_rank == 1
            assert c.blamed_span == "data/load"
            assert c.n_ranks == 3
            assert c.skew_ms == pytest.approx(40.0, abs=1e-6)
            assert c.wire_ms == pytest.approx(10.0, abs=1e-6)

    def test_rank_step_skew_charges_the_waiters(self):
        """The laggard itself waited 0; everyone else waited the full
        entry skew — that is what note_pod_skew consumes."""
        pod = podview.PodTimeline.merge(
            _pod_events({0: 0.0, 1: 0.0, 2: 0.0}, n_steps=1,
                        slow_rank=2, slow_ms=25.0))
        rss = pod.rank_step_skew()
        assert rss[(0, 0)] == pytest.approx(20.0, abs=1e-6)
        assert rss[(1, 0)] == pytest.approx(20.0, abs=1e-6)
        assert (2, 0) not in rss

    def test_critical_path_chains_wait_then_wire(self):
        pod = podview.PodTimeline.merge(
            _pod_events({0: 0.0, 1: 42.0}, n_steps=2, slow_rank=0,
                        slow_ms=30.0))
        path = pod.critical_path(1)
        assert [s["segment"] for s in path] == ["wait", "wire"]
        assert path[0]["rank"] == 0
        assert path[0]["span"] == "data/load"
        assert path[0]["dur_ms"] == pytest.approx(25.0, abs=1e-4)
        assert path[1]["dur_ms"] == pytest.approx(10.0, abs=1e-4)

    def test_goodput_join_round_trip_closure(self):
        """pod merge → rank_step_skew → note_pod_skew: the waiter's
        collective time splits into skew + wire and still closes."""
        pod = podview.PodTimeline.merge(
            _pod_events({0: 0.0, 1: 0.0}, n_steps=1, slow_rank=1,
                        slow_ms=35.0))
        skew = pod.rank_step_skew()[(0, 0)]
        ledger = GoodputLedger(rank=0)
        ledger.note_pod_skew(skew, step=0)
        st = StepTrace(0, 0.0)
        st.dur_ms = 50.0
        st.spans.append(SpanEvent("data/load", "span", 0.0, 5.0, 0))
        # rank 0's collective span covers its wait + the wire time
        st.spans.append(SpanEvent("grad/allreduce", "collective",
                                  0.005, 40.0, 0))
        ledger.on_step(st)
        rec = ledger.steps[0]
        assert rec.buckets["comm_skew"] == pytest.approx(30.0)
        assert rec.buckets["comm_wire"] == pytest.approx(10.0)
        assert rec.exposed_comm == pytest.approx(40.0)
        assert sum(rec.buckets.values()) == pytest.approx(50.0)
        assert rec.closure_error() < 1e-9


# --- exports -----------------------------------------------------------------

class TestExports:
    def test_chrome_trace_process_metadata(self):
        events = _pod_events({0: 0.0, 1: 20.0})
        events.append(_span("data/load", "span", 0, 9, 8e6, 2.0))
        pod = podview.PodTimeline.merge(events)
        trace = pod.chrome_trace()
        names = {e["pid"]: e["args"]["name"]
                 for e in trace["traceEvents"]
                 if e.get("name") == "process_name"}
        assert names[0] == "rank 0"
        assert names[1] == "rank 1"
        assert names[9] == "rank 9 (unaligned)"
        sorts = {e["pid"]: e["args"]["sort_index"]
                 for e in trace["traceEvents"]
                 if e.get("name") == "process_sort_index"}
        assert sorts == {0: 0, 1: 1, 9: 9}
        spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert all(e["pid"] in (0, 1, 9) for e in spans)
        assert trace["metadata"]["reference_rank"] == 0

    def test_aligned_collective_entries_line_up(self):
        """After alignment, both ranks' collective entry edges sit at
        the same pod-clock instant minus the real entry skew."""
        pod = podview.PodTimeline.merge(
            _pod_events({0: 0.0, 1: 500.0}, n_steps=1))
        coll = {r: tl.collectives()[(0, "grad/allreduce", 0)]
                for r, tl in pod.timelines.items()}
        t0 = pod.aligned(coll[0])[0]
        t1 = pod.aligned(coll[1])[0]
        assert t1 - t0 == pytest.approx(0.0, abs=1e-6)

    def test_events_validate_and_stream_through_channel(self, tmp_path):
        check = _schema()
        pod = podview.PodTimeline.merge(
            _pod_events({0: 0.0, 1: 7.5}, n_steps=2, slow_rank=1,
                        slow_ms=15.0))
        events = pod.to_events(wall_time=time.time())
        assert check([json.dumps(e) for e in events]) == []
        path = tmp_path / "podview.jsonl"
        logger = monitor.MetricsLogger(
            podview_sink=monitor.JSONLSink(str(path)))
        for ev in events:
            logger.record_podview(ev)    # unbuffered: lands immediately
        lines = path.read_text().strip().splitlines()
        assert len(lines) == len(events)
        assert check(lines) == []
        logger.close()

    def test_committed_fixture_validates(self):
        """The pod_audit fixture in CI is schema-clean and carries the
        blame the audit asserts."""
        check = _schema()
        lines = open(_FIXTURE).read().strip().splitlines()
        assert check(lines) == []
        recs = [json.loads(ln) for ln in lines]
        skews = [r for r in recs if r["kind"] == "pod_skew"]
        assert skews and all(r["blamed_rank"] == 2 and
                             r["blamed_span"] == "data/load"
                             for r in skews)

    def test_negative_twins_rejected(self):
        check = _schema()
        good = {"kind": "pod_align", "rank": 1, "offset_ms": 3.0,
                "drift_ppm": 0.0, "residual_ms": 0.1, "n_shared": 4,
                "aligned": True, "reference": 0, "wall_time": 1.0}
        assert check([json.dumps(good)]) == []
        # a non-reference rank claiming alignment with nothing shared
        bad = dict(good, n_shared=0)
        assert check([json.dumps(bad)]) != []
        # stale must be a boolean, ratio positive
        drift = {"kind": "pod_drift", "hop": 0, "op": "all_reduce",
                 "axis": "data", "link": "ici", "dtype": None,
                 "predicted_ms": 1.0, "measured_ms": 2.0, "ratio": 2.0,
                 "stale": False,
                 "fingerprint": "comm_drift|all_reduce|data/ici",
                 "wall_time": 1.0}
        assert check([json.dumps(drift)]) == []
        assert check([json.dumps(dict(drift, stale="no"))]) != []
        assert check([json.dumps(dict(drift, ratio=-1.0))]) != []
        assert check([json.dumps(dict(drift, link="pcie"))]) != []


# --- comm drift --------------------------------------------------------------

def _plan():
    hops = (Hop("reduce_scatter", "data_intra", 4, "ici", None,
                alpha_us=1.0, bytes_per_s=1e9, calibrated=False),
            Hop("all_reduce", "data_inter", 2, "dcn", None,
                alpha_us=10.0, bytes_per_s=1e8, calibrated=False),
            Hop("all_gather", "data_intra", 4, "ici", None,
                alpha_us=1.0, bytes_per_s=1e9, calibrated=False))
    return CommPlan(hops=hops, compress_block=256, source="defaults",
                    mesh_name="testmesh", grad_bytes=1 << 20)


class TestCommDrift:
    def test_compare_within_band_not_stale(self):
        plan = _plan()
        rep = comm_drift.compare(plan, plan.hop_seconds(),
                                 tolerance=4.0)
        assert not rep.stale
        assert rep.drift_ratio == pytest.approx(1.0)
        assert rep.advice() is None
        assert rep.plan_source == "defaults"
        assert "holds" in rep.table()

    def test_stale_hop_fires_with_fingerprint_and_advice(self):
        plan = _plan()
        measured = plan.hop_seconds()
        measured[1] *= 100.0          # the DCN hop went bad
        rep = comm_drift.compare(plan, measured, tolerance=4.0)
        assert rep.stale
        assert [h.hop for h in rep.stale_hops()] == [1]
        fp = rep.stale_hops()[0].fingerprint
        assert fp == "comm_drift|all_reduce|data_inter/dcn"
        assert "scripts/link_probe.py" in rep.advice()
        assert rep.drift_ratio == pytest.approx(100.0, rel=1e-6)
        # symmetric band: a hop measuring far *under* prediction is
        # equally a model that does not describe the fabric
        slow_model = plan.hop_seconds()
        slow_model[0] /= 100.0
        assert comm_drift.compare(plan, slow_model,
                                  tolerance=4.0).stale

    def test_compare_rejects_hop_count_mismatch(self):
        with pytest.raises(ValueError):
            comm_drift.compare(_plan(), [1e-3, 2e-3])

    def test_wire_from_pod_positional_join(self):
        """Hop-position join: the hierarchical sync names sub-spans by
        link class in hop order, so occurrence j of "ici" maps to the
        j-th ici hop."""
        plan = _plan()
        events = []
        for rank in (0, 1):
            t = 0.0
            for name, d in (("ici", 10.0), ("dcn", 20.0),
                            ("ici", 5.0)):
                events.append(_span(name, "collective", 0, rank, t, d))
                t += d
        pod = podview.PodTimeline.merge(events)
        wires = comm_drift.wire_from_pod(pod, plan)
        assert wires == pytest.approx([10e-3, 20e-3, 5e-3], abs=1e-9)

    def test_wire_from_pod_missing_hop_returns_none(self):
        plan = _plan()
        events = [_span("ici", "collective", 0, r, 0.0, 10.0)
                  for r in (0, 1)]
        pod = podview.PodTimeline.merge(events)
        assert comm_drift.wire_from_pod(pod, plan) is None

    def test_drift_events_validate(self):
        check = _schema()
        plan = _plan()
        measured = plan.hop_seconds()
        measured[2] *= 50.0
        rep = comm_drift.compare(plan, measured, tolerance=4.0)
        lines = [json.dumps(e)
                 for e in rep.to_events(wall_time=time.time())]
        assert check(lines) == []
        recs = [json.loads(ln) for ln in lines]
        assert [r["stale"] for r in recs] == [False, False, True]
