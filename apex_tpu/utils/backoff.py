"""Jittered exponential backoff — the one implementation every
host-side retry seam shares (ckpt shared-fs barrier/manifest/gather,
loader decode IO, elastic restart ladder).

Two properties every caller relies on:

- **de-phased**: jitter draws from ``random.SystemRandom``, never the
  seedable global RNG — N ranks that all called ``random.seed(cfg.seed)``
  for reproducibility would otherwise draw IDENTICAL "jitter" and still
  poll a shared filesystem in lockstep (the thundering herd the jitter
  exists to break), and a retry loop consuming the global stream would
  make user code after it nondeterministic in the number of
  latency-dependent draws;
- **bounded**: ``min(cap_s, base_s · 2^attempt)``, so a caller sitting
  on a latency-sensitive path (a blocking save's commit barrier on the
  main thread, where poll latency is watchdog-heartbeat latency) can
  pin the cap low while still getting exponential shape.
"""

from __future__ import annotations

import random
import time

__all__ = ["backoff_sleep"]

_jitter = random.SystemRandom()


def backoff_sleep(attempt: int, *, base_s: float = 0.02,
                  cap_s: float = 1.0) -> float:
    """Sleep ``min(cap_s, base_s · 2^attempt)`` scaled by a uniform
    [0.5, 1.5) jitter; returns the slept time."""
    t = min(cap_s, base_s * (2.0 ** attempt)) * (0.5 + _jitter.random())
    time.sleep(t)
    return t
