"""Elastic restore: re-partition ZeRO shards for a different mesh size.

The ZeRO arena layout makes elasticity *arithmetic* instead of a
migration: a slot buffer's logical content is its first ``buffer_len``
elements (the arena padding — and everything the optimizer ever writes
past it — is identically zero: zero grads meet zero moments meet zero
masters, see ``DistributedFusedAdam.init``), and the only world-size
dependence is the trailing padding ``_padded_len(buffer_len, world)``
that makes the buffer divide into aligned shards. So resuming on a
different ``zero_size`` is::

    gather (by manifest)  →  truncate to buffer_len  →
    re-pad to _padded_len(buffer_len, new_world)     →
    re-scatter (device_put with the new mesh's sharding)

— bitwise-exact: every logical element is a memcpy, every padding
element is zero on both sides. ``tests/test_ckpt.py`` pins the
end-to-end property (8-device training resumed on 4 devices equals an
uninterrupted 4-device run bitwise).

:func:`zero_layout` computes the ``path → buffer_len`` map the manifest
records, by walking the state pytree for ``ShardedOptState`` nodes and
joining their slot dict keys (the partition dtype names) against the
``arena.plan`` of the params.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

__all__ = ["partition_lengths", "repartition_flat", "zero_layout"]


def partition_lengths(spec) -> Dict[str, int]:
    """``dtype → logical buffer length`` for an ``arena.ArenaSpec`` —
    THE one derivation of the elastic-restore lengths, shared by
    :func:`zero_layout` (per-leaf manifest map) and
    ``DistributedFusedAdam.checkpoint_layout`` (user-facing
    introspection), so the two can never drift."""
    return {p.dtype: int(p.buffer_len) for p in spec.partitions}


def repartition_flat(buf: np.ndarray, logical_len: int,
                     new_total: int) -> np.ndarray:
    """Re-partition one gathered flat ZeRO buffer to a new padded total.

    ``buf`` is the full gathered buffer from the old mesh (length =
    ``_padded_len(logical_len, old_world)``), ``logical_len`` the
    arena partition's ``buffer_len``, ``new_total`` the target length
    (``_padded_len(logical_len, new_world)`` — in practice simply the
    like-tree leaf's length). Truncate + zero-pad; content is never
    resampled.
    """
    buf = np.asarray(buf)
    if buf.ndim != 1:
        raise ValueError(f"ZeRO slot buffers are 1-D, got {buf.shape}")
    if logical_len > buf.shape[0]:
        raise ValueError(
            f"saved buffer ({buf.shape[0]}) shorter than its recorded "
            f"logical length ({logical_len}) — corrupt manifest?")
    if new_total < logical_len:
        raise ValueError(
            f"target length {new_total} cannot hold the {logical_len} "
            f"logical elements — the new mesh's shard alignment should "
            f"only ever grow the padded total")
    logical = buf[:logical_len]
    if new_total == logical_len:
        return logical
    out = np.zeros((new_total,), dtype=buf.dtype)
    out[:logical_len] = logical
    return out


def zero_layout(state: Any, params: Any = None,
                spec: Any = None) -> Dict[str, int]:
    """``path → logical_len`` for every ZeRO slot-buffer leaf in
    ``state`` (empty when the state holds no ``ShardedOptState`` — a
    plain-DDP checkpoint needs no elasticity metadata).

    Pass the ``params`` the optimizer was initialized from (or a
    prebuilt ``arena.ArenaSpec``) so the slot dict's dtype keys resolve
    to partition lengths.
    """
    import jax
    from apex_tpu.optim.distributed import ShardedOptState

    found = [
        (path, leaf) for path, leaf in
        jax.tree_util.tree_flatten_with_path(
            state, is_leaf=lambda x: isinstance(x, ShardedOptState))[0]
        if isinstance(leaf, ShardedOptState)
    ]
    if not found:
        return {}
    if spec is None:
        if params is None:
            raise ValueError(
                "state contains ZeRO-sharded optimizer state; pass "
                "params= (or spec=) so the checkpoint can record each "
                "slot buffer's logical length for elastic restore")
        from apex_tpu import arena
        spec = arena.plan(params)
    lengths = partition_lengths(spec)
    out: Dict[str, int] = {}
    for prefix, sos in found:
        for subpath, _leaf in jax.tree_util.tree_flatten_with_path(
                sos)[0]:
            # slot-buffer leaves end in (DictKey(slot), DictKey(dtype));
            # the count scalar has no dict suffix and stays replicated
            if len(subpath) < 2:
                continue
            last = subpath[-1]
            dt = getattr(last, "key", None)
            if dt is None or dt not in lengths:
                continue
            path = jax.tree_util.keystr(tuple(prefix) + tuple(subpath))
            out[path] = lengths[dt]
    return out
