"""apexlint — static analysis for compiled training steps.

The reference apex kept mixed-precision training correct *by
construction* (cast lists, opt-level validation at initialize time);
apexlint closes the remaining gap by auditing what was actually traced
and compiled. Three passes, all strictly AOT (trace + compile only —
never a device dispatch; the ``lint/no-extra-dispatch`` compile-check
case pins that an observed step stays bit-identical):

- the **jaxpr pass** (:mod:`apex_tpu.lint.jaxpr_pass`) walks
  ``jax.make_jaxpr`` output: RNG-key reuse, f64 creep, fp32 matmuls
  inside an active half-precision amp policy, host callbacks / debug
  prints traced into the step;
- the **HLO pass** (:mod:`apex_tpu.lint.hlo_pass`) walks the optimized
  scheduled HLO (reusing :mod:`apex_tpu.prof.memory`'s buffer parser
  and the :mod:`apex_tpu.monitor` collective accounting): donation
  misses with wasted-HBM estimates, collectives outside any known
  named scope (implicit resharding) with wire-byte cost, host
  transfers, and off-tile-grid matmul padding waste;
- the **SPMD pass** (:mod:`apex_tpu.lint.spmd_pass`) audits the
  *cross-rank* properties no per-program rule sees: collective
  schedule congruence across ranks (mismatched replica groups /
  channel ids deadlock a pod — APX201), sharding propagation's
  implicit full all-gathers (APX202), flat reductions crossing a DCN
  boundary that wanted a hierarchical schedule (APX203, judged against
  a declarative :mod:`mesh model <apex_tpu.lint.mesh_model>`), and
  nondeterministic draws that break guard's bitwise-rewind oracle
  (APX204 — this one needs no mesh and runs in every ``lint_step``);
- the **precision pass** (:mod:`apex_tpu.lint.precision_pass`) runs a
  dtype-provenance abstract interpretation over the *same single
  trace* the jaxpr pass reads: unscaled narrow casts (APX301),
  double rounding (APX302), loss-scale taint leaking into committed
  outputs (APX303), half-precision update arithmetic with no f32
  master under an O2/O3 policy (APX304), half-accumulating
  dots/reductions (APX305), and — given a committed
  ``precision_report`` fixture — collective wire dtypes narrower than
  the measured per-site verdicts (APX306, the static×measured join).
  :func:`precision_preflight` inverts the join into the ranked
  "statically castable ∩ measured-safe" site list that gates fp8/O4.

Typical use — lint the step exactly as you run it (pass your jitted
function so its ``donate_argnums`` are what gets audited)::

    jstep = jax.jit(train_step, donate_argnums=(0, 1))
    report = lint.lint_step(jstep, state, batch_stats, x, y,
                            policy=policy)
    print(report.table())
    assert not report.errors

Pod-scale pre-flight — add a mesh model and the cross-rank rules run
over the same compile::

    mm = lint.parse_mesh_spec("dp2x4")       # 2 slices (DCN) x 4 (ICI)
    report = lint.lint_step(jstep, *args, mesh_model=mm)

CLI: ``python scripts/apexlint.py --flagship both`` (the
``run_tier1.sh --smoke`` CI gate; add ``--mesh dp2x4`` for the
cross-rank congruence audit), or ``--hlo dump.txt`` for a pre-dumped
module. Findings stream to JSONL via
``MetricsLogger(lint_sink=...)`` and validate with
``scripts/check_metrics_schema.py --kind lint``. Rule catalog,
severities, the mesh-model schema and the baseline-file workflow:
docs/linting.md.
"""

from __future__ import annotations

from typing import Optional, Sequence

from apex_tpu.lint.findings import (Finding, Report, Rule, RULES,
                                    SEVERITIES, DTYPE_NAMES,
                                    PROVENANCES, load_baseline,
                                    save_baseline)
from apex_tpu.lint.hlo_pass import lint_hlo_text
from apex_tpu.lint.jaxpr_pass import lint_jaxpr
from apex_tpu.lint.mesh_model import (MeshAxis, MeshModel,
                                      parse_mesh_spec)
from apex_tpu.lint.precision_pass import (PrecisionAnalysis,
                                          PreflightResult,
                                          analyze_jaxpr as
                                          precision_analysis,
                                          precision_findings,
                                          precision_preflight,
                                          wire_dtype_findings)
from apex_tpu.lint.spmd_pass import (congruence_findings,
                                     extract_collective_schedule,
                                     lint_spmd_text,
                                     nondeterminism_jaxpr_findings)

__all__ = ["Finding", "Report", "Rule", "RULES", "SEVERITIES",
           "DTYPE_NAMES", "PROVENANCES",
           "lint_step", "lint_jaxpr", "lint_hlo_text", "lint_hlo_file",
           "load_baseline", "save_baseline",
           "MeshAxis", "MeshModel", "parse_mesh_spec",
           "lint_spmd_text", "congruence_findings",
           "extract_collective_schedule",
           "nondeterminism_jaxpr_findings",
           "PrecisionAnalysis", "PreflightResult",
           "precision_analysis", "precision_findings",
           "precision_preflight", "wire_dtype_findings"]

#: jaxpr-pass rule slugs (trace-only); nondeterminism's jaxpr-side
#: detectors ride the same single trace
_JAXPR_RULES = frozenset({"rng-key-reuse", "f64-creep",
                          "fp32-matmul-in-amp", "host-callback-in-step",
                          "nondeterminism"})
_HLO_RULES = frozenset({"donation-miss", "implicit-resharding",
                        "host-transfer", "tile-padding"})
_SPMD_HLO_RULES = frozenset({"spmd-divergence", "implicit-full-gather",
                             "dcn-flat-collective"})
#: precision-pass rule slugs; the first five are trace-only and ride
#: the shared jaxpr, APX306 additionally needs the compiled HLO's
#: collective schedule plus a measured precision_report (precision=)
_PRECISION_RULES = frozenset({"unscaled-narrow-cast", "double-rounding",
                              "scale-leak", "master-weight-violation",
                              "half-accumulation"})
_WIRE_RULE = "wire-dtype-unsafe"


def lint_step(fn, *args, policy=None, compiled=None, hlo_text=None,
              known_scopes: Sequence[str] = (),
              min_donation_bytes: int = 4096,
              rules: Optional[Sequence[str]] = None,
              mesh_model: Optional[MeshModel] = None,
              per_rank_hlo=None, precision=None, jaxpr=None,
              fn_name: Optional[str] = None, **kwargs) -> Report:
    """Lint one training step with all passes. Strictly AOT.

    ``fn`` may be a plain callable or a jitted function — pass the
    jitted one so the HLO pass sees your real ``donate_argnums``
    (donation is part of what is being audited). The jaxpr pass traces
    ``fn`` with ``jax.make_jaxpr`` (once — the APX204 nondeterminism
    detectors read the same trace); the HLO pass compiles it (or reuses
    ``compiled=`` / ``hlo_text=`` when the caller already has the
    executable, avoiding a second compile). ``policy`` activates the
    fp32-matmul-in-amp rule; ``known_scopes`` extends the
    implicit-resharding allowlist (regex fragments).

    ``mesh_model`` (a :class:`MeshModel`, e.g.
    ``parse_mesh_spec("dp2x4")``) activates the cross-rank SPMD rules
    over the compiled module: congruence/deadlock (APX201), implicit
    full gathers (APX202 — subsumes APX102's generic warning for
    all-gather ops, which is dropped to avoid double reports), and
    DCN-crossing flat collectives (APX203). ``per_rank_hlo`` (a
    ``{rank: hlo_text}`` dict) feeds per-rank-compiled programs to the
    congruence walk instead of the single SPMD module.

    ``precision`` controls the precision pass: the default ``None``
    runs the trace-side rules (APX301–305) on the shared jaxpr;
    ``False`` disables the pass; a measured ``precision_report`` — a
    ``NumericsReport``, or the stats dict / ``stats_to_json`` fixture
    it is built from — additionally activates APX306, joining the
    compiled module's collective wire dtypes against the per-site
    verdicts. ``jaxpr=`` accepts an already-made trace so external
    callers (bench, the CLI's preflight) share it; all jaxpr-side
    passes here always share ONE trace either way.
    """
    import jax

    findings = []
    rule_set = None if rules is None else set(rules)
    want_jaxpr_pass = rule_set is None or bool(_JAXPR_RULES & rule_set)
    want_precision = (precision is not False
                      and (rule_set is None
                           or bool(_PRECISION_RULES & rule_set)
                           or _WIRE_RULE in rule_set))
    if (jaxpr is None and fn is not None
            and (want_jaxpr_pass or want_precision)):
        # ONE trace shared by the jaxpr pass, APX204's detectors, and
        # the precision pass — and skipped entirely when the caller
        # selected HLO-pass rules only (with compiled= that makes
        # lint_step compile-free AND trace-free)
        jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    if jaxpr is not None and want_jaxpr_pass:
        findings += lint_jaxpr(jaxpr, policy=policy)
        if rule_set is None or "nondeterminism" in rule_set:
            findings += nondeterminism_jaxpr_findings(jaxpr)
    if jaxpr is not None and want_precision:
        findings += precision_findings(jaxpr, policy=policy)
    want_wire = (want_precision and precision is not None
                 and precision is not False
                 and (rule_set is None or _WIRE_RULE in rule_set))
    want_spmd = (mesh_model is not None or per_rank_hlo is not None
                 ) and (rules is None or _SPMD_HLO_RULES & set(rules))
    if hlo_text is None and (rules is None or _HLO_RULES & set(rules)
                             or want_spmd or want_wire):
        # same economy as the trace skip above: no XLA compile when the
        # caller selected jaxpr-pass rules only
        if compiled is not None:
            hlo_text = compiled.as_text()
        elif fn is not None:
            from apex_tpu.prof import hlo as _hlo
            hlo_text = _hlo.compiled_hlo(fn, *args, **kwargs)
    if hlo_text:
        findings += lint_hlo_text(
            hlo_text, known_scopes=known_scopes,
            min_donation_bytes=min_donation_bytes, rules=rules)
    if want_spmd and (hlo_text or per_rank_hlo):
        findings = _merge_spmd(findings, lint_spmd_text(
            per_rank_hlo if per_rank_hlo is not None else hlo_text,
            mesh_model=mesh_model, known_scopes=known_scopes,
            rules=rules))
    if want_wire and hlo_text:
        findings += wire_dtype_findings(
            extract_collective_schedule(hlo_text), precision,
            extra_scopes=known_scopes)
    if rules is not None:
        findings = [f for f in findings if f.rule in set(rules)]
    if fn_name is None and fn is not None:
        fn_name = getattr(fn, "__name__", None) or type(fn).__name__
    return Report(findings, fn_name=fn_name)


def _merge_spmd(findings, spmd):
    """Merge SPMD-pass findings into a finding list: APX202 carries the
    byte/axis/hop evidence for an unplanned all-gather, so the generic
    APX102 warning on the same op is redundant noise and dropped."""
    if any(f.rule == "implicit-full-gather" for f in spmd):
        findings = [f for f in findings
                    if not (f.rule == "implicit-resharding"
                            and f.op == "all-gather")]
    return findings + spmd


def lint_hlo_file(path: str, *, known_scopes: Sequence[str] = (),
                  min_donation_bytes: int = 4096,
                  mesh_model: Optional[MeshModel] = None) -> Report:
    """HLO-pass-only lint of a dumped optimized-HLO text file
    (``scripts/dump_hlo.py`` output or an XLA dump); a ``mesh_model``
    adds the cross-rank SPMD rules."""
    with open(path) as f:
        text = f.read()
    import os
    findings = lint_hlo_text(text, known_scopes=known_scopes,
                             min_donation_bytes=min_donation_bytes)
    if mesh_model is not None:
        findings = _merge_spmd(findings, lint_spmd_text(
            text, mesh_model=mesh_model, known_scopes=known_scopes))
    return Report(findings, fn_name=os.path.basename(path))
