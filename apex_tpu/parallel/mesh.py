"""Device-mesh construction helpers.

The reference's process topology is implicit: `torch.distributed` ranks plus
hand-built sub-groups (`apex/parallel/__init__.py:21-95` SyncBN groups,
`apex/parallel/distributed.py:604-624` round-robin allreduce groups,
`apex/contrib/optimizers/distributed_fused_adam.py:250-290` hierarchical
intra/inter-node groups). On TPU the topology is explicit and first-class: a
``jax.sharding.Mesh`` with named axes. Sub-groups become extra mesh axes —
a (nodes, local) factorization of the data axis gives the same hierarchy the
reference builds with ``dist.new_group``, except XLA routes each collective
over the right interconnect (ICI within an axis that lives inside a slice,
DCN across slices) automatically.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

#: Canonical axis names. data = DP/ZeRO sharding, model = tensor parallel,
#: seq = sequence/context parallel (ring attention), pipe = pipeline stages,
#: expert = MoE expert parallel.
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"

#: canonical names of the FACTORED data axes (the hierarchical
#: gradient-sync topology): ``data_inter`` crosses slices over DCN,
#: ``data_intra`` stays inside a slice on ICI — the same names
#: `hierarchical_data_mesh` builds and the ``dp2x4`` mesh-model spec
#: declares, so a plan, a mesh and a model line up by construction.
DATA_INTER_AXIS = "data_inter"
DATA_INTRA_AXIS = "data_intra"


def make_mesh(axis_sizes: Sequence[Tuple[str, int]],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a mesh from ``[(axis_name, size), ...]``.

    A size of -1 (at most one axis) absorbs the remaining devices, so
    ``make_mesh([("data", -1)])`` is the pure-DP mesh on any slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = [s for _, s in axis_sizes]
    names = [n for n, _ in axis_sizes]
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may have size -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed axes {known}")
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, have {len(devices)}")
    return Mesh(np.array(devices).reshape(sizes), tuple(names))


def data_parallel_mesh(devices=None) -> Mesh:
    """All devices on one ``data`` axis — the topology of the reference's
    DDP (`apex/parallel/distributed.py:129`)."""
    return make_mesh([(DATA_AXIS, -1)], devices)


def hierarchical_data_mesh(local_size: int, devices=None) -> Mesh:
    """Factorize data parallelism into (inter, intra) axes of sizes
    (world/local_size, local_size) — the two-level reduce-scatter/all-reduce
    layout of DistributedFusedAdam (`distributed_fused_adam.py:250-290`,
    intra-node group + inter-node group). Collectives over ``data_intra``
    ride the fast interconnect; ``data_inter`` crosses slices/hosts.
    """
    return make_mesh([(DATA_INTER_AXIS, -1), (DATA_INTRA_AXIS, local_size)],
                     devices)


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding that replicates a pytree leaf across the whole mesh."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def axis_size(axis: str, mesh: Optional[Mesh] = None) -> int:
    """Size of a named axis, inside shard_map (via lax) or outside (via
    mesh)."""
    if mesh is not None:
        return mesh.shape[axis]
    return jax.lax.axis_size(axis)


def local_batch(global_batch: int, mesh: Mesh, axis: str = DATA_AXIS) -> int:
    n = mesh.shape[axis]
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{axis}={n}")
    return global_batch // n
