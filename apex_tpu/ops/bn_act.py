"""Fused BatchNorm → (+residual) → ReLU with a minimal-residual VJP.

TPU rebuild of the reference's fused-BN CUDA family — the persistent
NHWC BN kernels (`apex/contrib/csrc/groupbn/nhwc_batch_norm_kernel.h`),
the add+relu fusion (`batch_norm_add_relu.cu`) and the hand-written
backward reductions (`csrc/welford.cu:259-903`). Those kernels exist to
cut HBM traffic: BN-backward under plain autodiff re-reads saved
activations several times (flax saves the input *and* x̂ *and* the relu
source), and on a memory-bound model that traffic is the MFU ceiling
(see PERF.md: the measured 80 GB/step vs the ~45 GB ideal graph).

The TPU answer is not a persistent kernel but *residual control*: one
``jax.custom_vjp`` unit covering BN → (+residual) → ReLU whose backward

- saves only the conv output ``x`` (already materialized in HBM — XLA
  dedups it with the copy the forward consumes) plus per-channel
  ``(mean, invstd)`` and, for the add+relu variant, the unit output
  ``z`` (also already saved: it is the next conv's input);
- recomputes ``x̂`` and the ReLU mask in-register instead of re-reading
  saved intermediates (`x̂γ+β > 0` for plain BN+ReLU, ``z > 0`` for the
  residual join);
- emits exactly the two irreducible HBM passes over ``(x, dy)``: one
  channel-sum reduce (Σdy, Σdy·x̂ — the `reduce_bn` stage of
  `optimized_sync_batchnorm_kernel.py:77-119`) and one elementwise dx
  pass.

Cross-device statistics (SyncBN / groupbn semantics) ride the same unit:
the forward combines per-device moments over ``axis_name`` (Welford,
exact for the stats-group case) and the backward ``psum``s the two
channel sums — the hand-derived collectives of the reference's SyncBN
backward, placed explicitly because autodiff no longer sees the stats.

Gradient note: the ``(mean, var, count)`` outputs exist for running-stat
EMA updates and are treated as ``stop_gradient`` — cotangents flowing
into them are ignored, matching torch BN semantics where running stats
are buffers.
"""

from __future__ import annotations

import functools
import os
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import use_interpret

__all__ = ["bn_act_train", "bn_add_act_train", "bn_act_reference",
           "FusedBNAct"]


class _Cfg(NamedTuple):
    """Static configuration (hashable — custom_vjp nondiff arg)."""
    relu: bool
    eps: float
    axis_name: Optional[str]
    groups: Optional[Tuple[Tuple[int, ...], ...]]
    #: store the backward-only activation residual as float8_e4m3 x̂
    #: instead of the full-precision conv output x (round-5 byte-floor
    #: experiment; see PERF.md round-5 ResNet section)
    fp8: bool = False


def _normalize_groups(axis_index_groups):
    if axis_index_groups is None:
        return None
    return tuple(tuple(int(i) for i in g) for g in axis_index_groups)


def _reduce_axes(x):
    return tuple(range(x.ndim - 1))  # channels-last (TPU-native NHWC)


def _local_count(x) -> float:
    n = 1
    for s in x.shape[:-1]:
        n *= s
    return float(n)


def _stats(x32, cfg: _Cfg):
    """Per-channel (mean, biased var, count), combined over the stats
    group when ``cfg.axis_name`` is set (count-weighted Welford — the
    `welford_parallel` combine, `csrc/welford.cu:905-1000`).

    Local moments are ONE-pass (E[x²]−E[x]², f32 accumulation over the
    half input): both channel sums fuse into the producing conv's
    epilogue, so the stats cost no standalone HBM pass. A two-pass
    centered variance cannot fuse there (the mean must complete first)
    and measured +13 GB/step on the ResNet-50 bench. f32 accumulation
    over BN-scale activations keeps the cancellation benign — the same
    trade cudnn's persistent BN kernels make; the *cross-device* combine
    still uses the stable Welford form."""
    axes = _reduce_axes(x32)
    mean = jnp.mean(x32, axis=axes)
    var = jnp.maximum(jnp.mean(jnp.square(x32), axis=axes)
                      - jnp.square(mean), 0.0)
    count = jnp.float32(_local_count(x32))
    if cfg.axis_name is None:
        return mean, var, count
    from apex_tpu.parallel.sync_batchnorm import _welford_combine
    means = jax.lax.all_gather(mean, cfg.axis_name,
                               axis_index_groups=cfg.groups)
    variances = jax.lax.all_gather(var, cfg.axis_name,
                                   axis_index_groups=cfg.groups)
    counts = jax.lax.all_gather(count, cfg.axis_name,
                                axis_index_groups=cfg.groups)
    return _welford_combine(means, variances, counts)


def _apply(x32, r, scale, bias, mean, invstd, relu):
    y = (x32 - mean) * (invstd * scale.astype(jnp.float32)) \
        + bias.astype(jnp.float32)
    if r is not None:
        y = y + r.astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y


def _xres_of(x, mean, invstd, cfg: _Cfg):
    """The backward's activation residual: x itself, or — under
    ``cfg.fp8`` — x̂ quantized to float8_e4m3. x̂ is zero-mean unit
    variance per channel BY CONSTRUCTION, so e4m3's dynamic range
    covers it with no per-channel scale factor; the backward consumes
    x only through x̂ (both channel sums and the dx term), so nothing
    else is lost. The expression duplicates _apply's interior on
    purpose: it fuses into the same normalize pass (reads x once,
    writes y and x̂₈), costing one fp8 write where the backward then
    reads fp8 twice instead of the wide dtype twice."""
    if not cfg.fp8:
        return x
    return ((x.astype(jnp.float32) - mean)
            * invstd).astype(jnp.float8_e4m3fn)


def _fwd_common(x, r, scale, bias, cfg: _Cfg):
    x32 = x.astype(jnp.float32)
    mean, var, count = _stats(x32, cfg)
    invstd = jax.lax.rsqrt(var + cfg.eps)
    z = _apply(x32, r, scale, bias, mean, invstd, cfg.relu).astype(x.dtype)
    return z, mean, var, count, invstd


# --- Pallas backward kernels ------------------------------------------------
#
# Measured on the ResNet-50 bench: expressing this backward in jnp lets
# XLA CSE the relu mask into a materialized pred[...] tensor (205 MB per
# layer1-class unit) and build 15-19-operand mega-fusions — 86.8 GB/step
# vs the 80.4 GB of plain autodiff. The two kernels below pin the
# intended traffic exactly: a sums pass and a dx pass, each reading
# (x, g-source) once, mask and x̂ recomputed in-register, nothing else
# materialized. This is the role of the reference's hand-written
# backward reductions (`csrc/welford.cu:259-903`,
# `batch_norm_add_relu.cu` dgrad).

def _bwd_row_block(m: int, c: int) -> int:
    """Rows per grid step: ~1 MiB half-dtype buffers (the addrelu sums
    kernel holds 4 of them double-buffered inside the 16 MiB scoped
    VMEM), a multiple of 8 that divides m exactly (so no padding copy of
    a 400 MB tensor is ever made). Returns 0 if no such divisor exists
    (caller falls back to the jnp backward)."""
    if m % 8:
        return 0
    target = max(8, min(4096, (1 << 20) // (2 * c) // 8 * 8))
    r = min(target, m)
    r -= r % 8
    while r >= 8 and m % r:
        r -= 8
    return max(r, 0)


def _sums_kernel(mode, x_ref, g_ref, *rest):
    refs = list(rest)
    z_ref = refs.pop(0) if mode == "addrelu" else None
    scale_ref, bias_ref, mean_ref, invstd_ref, sums_ref = refs[:5]
    dr_ref = refs[5] if mode == "addrelu" else None
    i = pl.program_id(0)

    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    xhat = (x - mean_ref[:]) * invstd_ref[:]
    if mode == "relu":
        g = jnp.where(xhat * scale_ref[:] + bias_ref[:] > 0, g, 0.0)
    elif mode == "addrelu":
        g = jnp.where(z_ref[:].astype(jnp.float32) > 0, g, 0.0)
        dr_ref[:] = g.astype(dr_ref.dtype)

    s_dy = jnp.sum(g, axis=0, keepdims=True)
    s_dyx = jnp.sum(g * xhat, axis=0, keepdims=True)
    rows = jax.lax.broadcasted_iota(jnp.int32, sums_ref.shape, 0)
    upd = jnp.where(rows == 0, s_dy, jnp.where(rows == 1, s_dyx, 0.0))

    @pl.when(i == 0)
    def _():
        sums_ref[:] = jnp.zeros_like(sums_ref)

    sums_ref[:] = sums_ref[:] + upd


def _dx_kernel(mode, x_ref, g_ref, scale_ref, bias_ref, mean_ref,
               invstd_ref, k1_ref, k2_ref, dx_ref):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    xhat = (x - mean_ref[:]) * invstd_ref[:]
    if mode == "relu":
        # recompute the mask; for "addrelu" g is the already-masked dr
        g = jnp.where(xhat * scale_ref[:] + bias_ref[:] > 0, g, 0.0)
    dx = (scale_ref[:] * invstd_ref[:]) * (g - k1_ref[:] - xhat * k2_ref[:])
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _bwd_pallas(cfg: _Cfg, x, scale, bias, mean, invstd, count, z, dz,
                has_residual: bool, r_dtype, rb: int):
    c = x.shape[-1]
    m = x.size // c
    x2 = x.reshape(m, c)
    g2 = dz.reshape(m, c)
    mode = ("addrelu" if (cfg.relu and has_residual)
            else "relu" if cfg.relu else "plain")

    row = lambda v: v.astype(jnp.float32).reshape(1, c)
    params = [row(scale), row(bias), row(mean), row(invstd)]

    blk = pl.BlockSpec((rb, c), lambda i: (i, 0), memory_space=pltpu.VMEM)
    prow = pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM)
    acc = pl.BlockSpec((8, c), lambda i: (0, 0), memory_space=pltpu.VMEM)
    interpret = use_interpret()

    # pass 1: channel sums (+ dr for the residual join)
    in_specs = [blk, blk] + ([blk] if mode == "addrelu" else []) \
        + [prow] * 4
    args = [x2, g2] + ([z.reshape(m, c)] if mode == "addrelu" else []) \
        + params
    out_specs = [acc]
    out_shapes = [jax.ShapeDtypeStruct((8, c), jnp.float32)]
    if mode == "addrelu":
        out_specs.append(blk)
        out_shapes.append(jax.ShapeDtypeStruct((m, c), r_dtype))
    res = pl.pallas_call(
        functools.partial(_sums_kernel, mode),
        grid=(m // rb,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shapes),
        interpret=interpret,
    )(*args)
    sums = res[0]
    dr2 = res[1] if mode == "addrelu" else None

    sum_dy, sum_dy_xhat = sums[0], sums[1]
    if cfg.axis_name is not None:
        sum_dy, sum_dy_xhat = jax.lax.psum(
            (sum_dy, sum_dy_xhat), cfg.axis_name,
            axis_index_groups=cfg.groups)

    k1 = (sum_dy / count).reshape(1, c)
    k2 = (sum_dy_xhat / count).reshape(1, c)

    # pass 2: dx. For the residual join g-source is dr (pre-masked), so
    # z is not re-read.
    g_src = dr2 if mode == "addrelu" else g2
    dx2 = pl.pallas_call(
        functools.partial(_dx_kernel,
                          "relu" if mode == "relu" else "plain"),
        grid=(m // rb,),
        in_specs=[blk, blk] + [prow] * 6,
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct((m, c), x.dtype),
        interpret=interpret,
    )(x2, g_src, *params, k1, k2)

    dx = dx2.reshape(x.shape)
    dscale = sum_dy_xhat.astype(scale.dtype)
    dbias = sum_dy.astype(bias.dtype)
    if has_residual:
        # no relu in the unit ⇒ dr is dz itself (identity add)
        dr = (dr2.reshape(x.shape) if dr2 is not None
              else dz.astype(r_dtype))
        return dx, dr, dscale, dbias
    return dx, dscale, dbias


def _bwd_core(cfg: _Cfg, x, scale, bias, mean, invstd, count, z, dz,
              has_residual: bool, r_dtype=None, dx_dtype=None):
    """Dispatch: jnp two-pass backward (the product path — XLA fuses it
    into exactly one reduce + one elementwise pass per unit). The Pallas
    variant exists behind ``APEX_TPU_BN_PALLAS_BWD=1``: measured on the
    bench it LOSES — XLA lays conv activations out as {3,0,2,1} (batch
    inside spatial) and a pallas custom-call pins default layouts, so
    every operand pays a 400 MB-class layout copy (see PERF.md round 3).
    """
    if os.environ.get("APEX_TPU_BN_PALLAS_BWD") == "1" and not cfg.fp8:
        c = x.shape[-1]
        rb = _bwd_row_block(x.size // c, c)
        if rb >= 8:
            return _bwd_pallas(cfg, x, scale, bias, mean, invstd, count,
                               z, dz, has_residual, r_dtype, rb)
    return _bwd_jnp(cfg, x, scale, bias, mean, invstd, count, z, dz,
                    has_residual, r_dtype, dx_dtype)


def _bwd_jnp(cfg: _Cfg, x, scale, bias, mean, invstd, count, z, dz,
             has_residual: bool, r_dtype=None, dx_dtype=None):
    """The two-pass minimal backward. Reads: (x, g-source) twice; writes
    dx[, dr]. x̂ is recomputed, never re-read.

    Mask handling is deliberately single-use so XLA cannot CSE it into a
    materialized pred tensor (measured: +6 GB/step on the bench when it
    does): for the residual join the mask folds into producing ``dr`` —
    an obligatory output — and the sums/dx passes then read ``dr``
    instead of (dz, z); for plain BN+ReLU the mask is recomputed from
    x̂γ+β inside each pass's fusion.
    """
    axes = _reduce_axes(x)
    cshape = (1,) * len(axes) + (-1,)
    mean_b = mean.reshape(cshape)
    invstd_b = invstd.reshape(cshape)
    scale32 = scale.astype(jnp.float32)

    def xhat_of(xv):
        if cfg.fp8:
            # the residual already IS x̂ (fp8); dequantize in-register
            return xv.astype(jnp.float32)
        return (xv.astype(jnp.float32) - mean_b) * invstd_b

    dr = None
    if cfg.relu and has_residual:
        # the unit output is the saved relu result (and the next conv's
        # input): z > 0 IS the mask. dr materializes ONCE (it is a
        # returned cotangent); everything downstream reads dr.
        dr = jnp.where(z > 0, dz, jnp.zeros((), dz.dtype)) \
            .astype(r_dtype if r_dtype is not None else dz.dtype)
        g_src = dr
    else:
        g_src = dz

    def masked(gv):
        g32 = gv.astype(jnp.float32)
        if cfg.relu and not has_residual:
            m = (xhat_of(x) * scale32.reshape(cshape)
                 + bias.astype(jnp.float32).reshape(cshape)) > 0
            g32 = jnp.where(m, g32, 0.0)
        return g32

    # pass 1: channel sums (fuses into one reduce over (x, g_src))
    g1 = masked(g_src)
    sum_dy = jnp.sum(g1, axis=axes)
    sum_dy_xhat = jnp.sum(g1 * xhat_of(x), axis=axes)
    if cfg.axis_name is not None:
        # the collectives the reference's hand-written SyncBN backward
        # issues (`optimized_sync_batchnorm_kernel.py:98-110`)
        sum_dy, sum_dy_xhat = jax.lax.psum(
            (sum_dy, sum_dy_xhat), cfg.axis_name,
            axis_index_groups=cfg.groups)

    # pass 2: dx (one elementwise fusion over (x, g_src))
    k1 = (sum_dy / count).reshape(cshape)
    k2 = (sum_dy_xhat / count).reshape(cshape)
    g2 = masked(g_src)
    xhat2 = xhat_of(x)
    dx = ((scale32 * invstd).reshape(cshape)
          * (g2 - k1 - xhat2 * k2)).astype(dx_dtype or x.dtype)
    dscale = sum_dy_xhat.astype(scale.dtype)
    dbias = sum_dy.astype(bias.dtype)
    if has_residual:
        if dr is None:          # no relu in the unit: identity add
            dr = dz.astype(r_dtype if r_dtype is not None else dz.dtype)
        return dx, dr, dscale, dbias
    return dx, dscale, dbias


# --- plain BN (+ReLU) --------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def bn_act_train(x, scale, bias, cfg: _Cfg):
    """Training-mode ``relu?(bn(x))`` over channels-last ``x``.

    Returns ``(z, mean, biased_var, count)``; the stat outputs are
    non-differentiable (running-stat feed). Build ``cfg`` via
    :func:`make_cfg`.
    """
    z, mean, var, count, _ = _fwd_common(x, None, scale, bias, cfg)
    return z, mean, var, count


def _bn_act_fwd(x, scale, bias, cfg):
    z, mean, var, count, invstd = _fwd_common(x, None, scale, bias, cfg)
    xres = _xres_of(x, mean, invstd, cfg)
    xtok = jnp.zeros((), x.dtype)       # dx dtype token
    return (z, mean, var, count), (xres, xtok, scale, bias, mean,
                                   invstd, count)


def _bn_act_bwd(cfg, res, cts):
    dz = cts[0]  # stat cotangents dropped: stats are buffers
    xres, xtok, scale, bias, mean, invstd, count = res
    dx, dscale, dbias = _bwd_core(cfg, xres, scale, bias, mean, invstd,
                                  count, None, dz, has_residual=False,
                                  dx_dtype=xtok.dtype)
    return dx, dscale, dbias


bn_act_train.defvjp(_bn_act_fwd, _bn_act_bwd)


# --- BN + residual add (+ReLU) ----------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def bn_add_act_train(x, r, scale, bias, cfg: _Cfg):
    """Training-mode ``relu?(bn(x) + r)`` — the residual-join unit
    (`batch_norm_add_relu.cu` semantics). Returns
    ``(z, mean, biased_var, count)``."""
    z, mean, var, count, _ = _fwd_common(x, r, scale, bias, cfg)
    return z, mean, var, count


def _bn_add_act_fwd(x, r, scale, bias, cfg):
    z, mean, var, count, invstd = _fwd_common(x, r, scale, bias, cfg)
    # z doubles as the relu mask source; it is consumed downstream (next
    # conv input) so saving it adds no HBM tensor
    zres = z if cfg.relu else None
    rtok = jnp.zeros((), r.dtype)  # dtype token (residual leaves: arrays)
    xres = _xres_of(x, mean, invstd, cfg)
    xtok = jnp.zeros((), x.dtype)
    return (z, mean, var, count), (xres, xtok, scale, bias, mean,
                                   invstd, count, zres, rtok)


def _bn_add_act_bwd(cfg, res, cts):
    dz = cts[0]
    xres, xtok, scale, bias, mean, invstd, count, z, rtok = res
    dx, dr, dscale, dbias = _bwd_core(cfg, xres, scale, bias, mean,
                                      invstd, count, z, dz,
                                      has_residual=True,
                                      r_dtype=rtok.dtype,
                                      dx_dtype=xtok.dtype)
    return dx, dr, dscale, dbias


bn_add_act_train.defvjp(_bn_add_act_fwd, _bn_add_act_bwd)


def make_cfg(*, relu: bool, eps: float = 1e-5,
             axis_name: Optional[str] = None,
             axis_index_groups=None, fp8: bool = False) -> _Cfg:
    return _Cfg(relu=bool(relu), eps=float(eps), axis_name=axis_name,
                groups=_normalize_groups(axis_index_groups),
                fp8=bool(fp8))


def bn_act_reference(x, scale, bias, *, residual=None, relu=True,
                     eps=1e-5):
    """Pure-jnp oracle (plain autodiff path) for tests."""
    x32 = x.astype(jnp.float32)
    axes = _reduce_axes(x)
    mean = jnp.mean(x32, axis=axes)
    var = jnp.mean(jnp.square(x32 - mean.reshape((1,) * len(axes) + (-1,))),
                   axis=axes)
    invstd = jax.lax.rsqrt(var + eps)
    y = _apply(x32, residual, scale, bias, mean, invstd, relu)
    return y.astype(x.dtype), mean, var


# --- flax module -------------------------------------------------------------

class FusedBNAct(nn.Module):
    """BatchNorm with optionally fused residual-add and ReLU, channels
    last, minimal-residual backward — the module surface of the
    reference's `BatchNorm2d_NHWC(fuse_relu=...)`
    (`apex/contrib/groupbn/batch_norm.py:18-90`) and the BN units inside
    the imagenet example's ResNet.

    Parameters/statistics are fp32 regardless of the activation dtype
    (keep_batchnorm_fp32); activations pass through in ``dtype``.
    Running stats follow the torch convention (unbiased var EMA), with
    the flax momentum convention ``ra = m·ra + (1−m)·new``.
    """
    num_features: int
    relu: bool = True
    momentum: float = 0.9
    epsilon: float = 1e-5
    axis_name: Optional[str] = None
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None
    init_scale: float = 1.0
    dtype: Optional[Any] = None
    #: fp8 backward-only residuals (or env APEX_TPU_FP8_RESIDUALS=1 at
    #: trace time); see _Cfg.fp8. Caveat (ADVICE r5): with ReLU the
    #: backward re-derives the activation mask from the *quantized* x̂,
    #: so activations within ~one e4m3 quantum of the y==0 boundary can
    #: receive gradients through a flipped mask — an extra noise source
    #: beyond the quantization noise itself. Fine for the opt-in
    #: memory-bandwidth experiment; don't expect bitwise-stable masks.
    fp8_residuals: bool = False

    @nn.compact
    def __call__(self, x, residual=None, train: bool = True):
        c = self.num_features
        if self.dtype is not None:
            x = x.astype(self.dtype)
            if residual is not None:
                residual = residual.astype(self.dtype)
        scale = self.param("scale",
                           nn.initializers.constant(self.init_scale),
                           (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda *_: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda *_: jnp.ones((c,), jnp.float32))

        if not train:
            inv = jax.lax.rsqrt(ra_var.value + self.epsilon)
            y = _apply(x.astype(jnp.float32), residual, scale, bias,
                       ra_mean.value, inv, self.relu)
            return y.astype(x.dtype)

        axis = None if self.is_initializing() else self.axis_name
        fp8 = (self.fp8_residuals
               or os.environ.get("APEX_TPU_FP8_RESIDUALS") == "1")
        cfg = make_cfg(relu=self.relu, eps=self.epsilon, axis_name=axis,
                       axis_index_groups=self.axis_index_groups,
                       fp8=fp8)
        if residual is None:
            z, mean, var, count = bn_act_train(x, scale, bias, cfg)
        else:
            z, mean, var, count = bn_add_act_train(x, residual, scale,
                                                   bias, cfg)

        if not self.is_initializing():
            unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * mean
            ra_var.value = m * ra_var.value + (1 - m) * unbiased
        return z
