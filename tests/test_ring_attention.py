"""Ring / Ulysses sequence parallelism vs full attention on one device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.ops import attention as A


def rand_qkv(rng, b, s, h, d):
    return (jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
            for _ in range(3))


def _run(mesh, fn, *args):
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=P(None, "data"), out_specs=P(None, "data"),
        check_vma=False))(*args)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh8, causal):
        rng = np.random.RandomState(0)
        q, k, v = rand_qkv(rng, 2, 8 * 32, 2, 32)

        def ring(q, k, v):
            return parallel.ring_attention(q, k, v, "data", causal=causal)

        got = _run(mesh8, ring, q, k, v)
        ref = A.attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=5e-5)

    def test_gradients_match(self, mesh8):
        rng = np.random.RandomState(1)
        q, k, v = rand_qkv(rng, 1, 8 * 16, 2, 32)

        def ring_loss(q, k, v):
            # local sum only: the global loss is the implicit sum of the
            # per-device losses, so each shard's grad is already global —
            # a psum here would double-count via its transpose
            o = parallel.ring_attention(q, k, v, "data", causal=True)
            return jnp.sum(jnp.sin(o))

        def g(q, k, v):
            return jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)

        got = jax.jit(jax.shard_map(
            g, mesh=mesh8, in_specs=P(None, "data"),
            out_specs=P(None, "data"), check_vma=False))(q, k, v)

        ref = jax.grad(
            lambda q_, k_, v_: jnp.sum(jnp.sin(
                A.attention_reference(q_, k_, v_, causal=True))),
            argnums=(0, 1, 2))(q, k, v)
        for a, e, name in zip(got, ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       atol=1e-4, err_msg=f"d{name}")


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh8, causal):
        rng = np.random.RandomState(2)
        q, k, v = rand_qkv(rng, 2, 8 * 32, 8, 16)  # 8 heads / 8 devices

        def uly(q, k, v):
            return parallel.ulysses_attention(q, k, v, "data",
                                              causal=causal)

        got = _run(mesh8, uly, q, k, v)
        ref = A.attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=5e-5)

    def test_gradients(self, mesh8):
        rng = np.random.RandomState(3)
        q, k, v = rand_qkv(rng, 1, 8 * 16, 8, 16)

        def loss(q, k, v):
            o = parallel.ulysses_attention(q, k, v, "data")
            return jnp.sum(o * o)

        def g(q, k, v):
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        got = jax.jit(jax.shard_map(
            g, mesh=mesh8, in_specs=P(None, "data"),
            out_specs=P(None, "data"), check_vma=False))(q, k, v)
        ref = jax.grad(
            lambda q_, k_, v_: jnp.sum(
                A.attention_reference(q_, k_, v_) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(got, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       atol=1e-4)
