"""One-stop step profiling: trace capture + per-op report + MFU.

Combines the capture (``jax.profiler.trace``), the xplane parser
(:mod:`apex_tpu.prof.xplane`) and XLA cost analysis
(:mod:`apex_tpu.prof.hlo`) into the workflow the reference needed three
tools for (nvtx annotate → nvprof → pyprof.parse → pyprof.prof):

    rep = prof.profile_step(step_fn, state, batch)
    print(rep.table())
    print(rep.mfu(peak_flops=197e12))
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import shutil
import tempfile
import time
from typing import Dict, List, Optional

import jax

from apex_tpu.prof import hlo as _hlo
from apex_tpu.prof import xplane as _xplane

__all__ = ["trace", "profile_step", "StepReport", "PEAK_FLOPS",
           "PEAK_HBM_BW", "VMEM_BYTES", "device_peak_flops",
           "device_peak_hbm_bw"]

# per-chip peak bf16 FLOP/s by device kind (public spec sheets)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# per-chip peak HBM bandwidth (bytes/s) by device kind — public spec
# sheets; PERF.md's measured steps sustain 97-98% of these, so the
# roofline denominator is honest. The bandwidth half of the peak table
# device_peak_flops starts (apex_tpu.prof.roofline reads both).
PEAK_HBM_BW = {
    "TPU v4": 1.228e12,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2.765e12,
    "TPU v5p": 2.765e12,
    "TPU v6 lite": 1.64e12,
    "TPU v6e": 1.64e12,
}

# per-chip VMEM capacity (bytes) — the on-chip scratch a Mosaic kernel
# tiles against (not a bandwidth: VMEM feeds the MXU at compute rate by
# construction, so a VMEM-resident working set never bounds a roofline;
# what DOES bound kernels is whether their tiles FIT — the autotuner's
# sweep constraint, see docs/profiling.md#roofline)
VMEM_BYTES = {
    "TPU v4": 128 << 20,
    "TPU v5 lite": 128 << 20,
    "TPU v5e": 128 << 20,
    "TPU v5": 112 << 20,
    "TPU v5p": 112 << 20,
    "TPU v6 lite": 128 << 20,
    "TPU v6e": 128 << 20,
}


def lookup_peak(table, kind: str) -> float:
    """Device-kind prefix match into a peak table, 0.0 when unknown
    (the one place the prefix-match semantics live — roofline_report
    resolves its explicit ``device_kind`` strings through here too)."""
    for k, v in table.items():
        if kind.startswith(k):
            return v
    return 0.0


def _device_kind(device) -> str:
    device = device or jax.devices()[0]
    return getattr(device, "device_kind", "cpu")


def device_peak_flops(device=None) -> float:
    """Peak bf16 FLOP/s of a jax device, 0.0 if unknown (CPU)."""
    return lookup_peak(PEAK_FLOPS, _device_kind(device))


def device_peak_hbm_bw(device=None) -> float:
    """Peak HBM bytes/s of a jax device, 0.0 if unknown (CPU)."""
    return lookup_peak(PEAK_HBM_BW, _device_kind(device))


@contextlib.contextmanager
def trace(logdir: str, **kwargs):
    """Capture a profiler trace to ``logdir`` (jax.profiler.trace shim)."""
    with jax.profiler.trace(logdir, **kwargs):
        yield logdir


@dataclasses.dataclass
class StepReport:
    """Profile of one jitted step: measured per-op times + static costs."""

    profile: _xplane.TraceProfile     # measured device activity
    cost: Dict[str, float]            # XLA cost analysis of the step
    wall_us: float                    # host wall time per iteration
    iters: int
    logdir: str

    @property
    def device_us(self) -> float:
        """Measured device time per iteration (XLA module runs)."""
        if self.profile.module_runs:
            return self.profile.module_total_us / self.profile.module_runs
        return self.wall_us

    def mfu(self, peak_flops: Optional[float] = None) -> float:
        """Model FLOPs utilization vs the chip's peak, from measured time."""
        peak = device_peak_flops() if peak_flops is None else peak_flops
        if not peak or not self.cost["flops"]:
            return 0.0
        return self.cost["flops"] / (self.device_us * 1e-6) / peak

    def by_category(self) -> Dict[str, float]:
        return self.profile.by_category()

    def table(self, top: int = 20) -> str:
        # unknown device kind (CPU, new chips): mfu() computes 0.0 only
        # because the peak is unknown — print n/a, not a misleading 0%
        mfu_s = f"{self.mfu():.1%}" if device_peak_flops() else "n/a"
        head = (f"device={self.profile.device or '(none)'} "
                f"iters={self.iters} wall/iter={self.wall_us:.0f}us "
                f"device/iter={self.device_us:.0f}us "
                f"flops={self.cost['flops']:.3g} "
                f"bytes={self.cost['bytes_accessed']:.3g} "
                f"mfu={mfu_s}")
        cats = "  ".join(f"{k}={v:.0f}us" for k, v in
                         list(self.by_category().items())[:8])
        return "\n".join([head, cats, self.profile.table(top=top)])


def profile_step(fn, *args, iters: int = 5, warmup: int = 2,
                 logdir: Optional[str] = None, keep_trace: bool = False,
                 **kwargs) -> StepReport:
    """Profile a jittable step function end to end.

    Jits (if needed), warms up ``warmup`` calls, then runs ``iters``
    calls under a profiler trace and parses the resulting xplane into
    per-op records. Works with functions returning pytrees; results are
    synced via host fetch of one leaf (block_until_ready is unreliable on
    the experimental axon platform — see bench.py).

    When no ``logdir`` is given a temp dir holds the trace and is
    **removed after parsing** (every record the report needs is already
    in the returned ``StepReport``); pass ``keep_trace=True`` to keep it
    for offline tools (tensorboard, ``python -m apex_tpu.prof``). An
    explicit ``logdir`` is always the caller's to clean up.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    own_tmpdir = logdir is None
    logdir = logdir or tempfile.mkdtemp(prefix="apex_tpu_prof_")

    def _sync(out):
        leaves = jax.tree_util.tree_leaves(out)
        if leaves:
            import numpy as np
            np.asarray(jax.device_get(leaves[0]))

    try:
        for _ in range(max(warmup, 1)):
            out = jitted(*args, **kwargs)
        _sync(out)

        t0 = time.perf_counter()
        with trace(logdir):
            for _ in range(iters):
                out = jitted(*args, **kwargs)
            _sync(out)
        wall = (time.perf_counter() - t0) / iters

        cost = _hlo.cost_analysis(jitted, *args, **kwargs)
        prof = _xplane.parse_trace(logdir)
    finally:
        if own_tmpdir and not keep_trace:
            shutil.rmtree(logdir, ignore_errors=True)
            logdir = ""
    return StepReport(profile=prof, cost=cost, wall_us=wall * 1e6,
                      iters=iters, logdir=logdir)
