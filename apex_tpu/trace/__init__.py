"""apex_tpu.trace — distributed tracing + flight recorder.

The forensic layer over :mod:`apex_tpu.monitor` (which tells you *that*
training is unhealthy) and :mod:`apex_tpu.prof` (post-hoc device
profiles): span-level step timelines, crash dumps, hang detection, and
NaN provenance, designed so a wedged multi-host run is diagnosable from
artifacts. See docs/tracing.md. Four pieces:

- **spans** (:mod:`~apex_tpu.trace.spans`): ``trace.span("fwd")``
  context manager/decorator layering ``jax.named_scope`` +
  ``jax.profiler.TraceAnnotation`` (device attribution via xplane) over
  a host wall-clock timeline per step (:class:`Tracer`), exported as
  Chrome-trace JSON (Perfetto-loadable) and a :class:`StepTimeline`
  table;
- **flight recorder** (:mod:`~apex_tpu.trace.recorder`): bounded ring of
  the last N step records (span timings, Metrics snapshot, loss scale,
  collective bytes, rank/host ids) with chained ``sys.excepthook`` /
  ``SIGTERM`` / ``atexit`` handlers that dump a JSONL crash report —
  rank, last-completed span, in-flight collective — on abnormal exit;
- **hang watchdog** (:mod:`~apex_tpu.trace.watchdog`): a daemon thread
  that fires when no step completes within a deadline, dumping all
  Python thread stacks plus the flight record and tagging the silent
  rank;
- **NaN provenance** (:mod:`~apex_tpu.trace.debug_nans`): opt-in
  ``debug_nans`` mode adding ``jax.debug.callback`` finiteness probes
  per span; the off path is bit-identical compiled HLO (the
  ``trace/no-extra-dispatch`` compile-check case);
- **straggler detection** (:mod:`~apex_tpu.trace.straggler`): per-rank
  shared-fs step heartbeats + a lockstep reader flagging persistent
  laggards (median-lag z-score with hysteresis) with the slowest span
  class on the lagging rank — the early-warning tier below the
  watchdog's hard stall deadline
  (:meth:`HangWatchdog.early_warning`);
- **pod observatory** (:mod:`~apex_tpu.trace.podview`): merges N
  ranks' span streams onto one clock (least-squares offsets over
  shared collective exits), splits every collective into
  wait-for-laggard vs wire time with (rank, span) blame, extracts the
  per-step cross-rank critical path, and exports a labeled merged
  Perfetto trace + ``podview``-channel events
  (``scripts/pod_audit.py --cpu8``; docs/tracing.md#podview).
"""

from apex_tpu.trace.debug_nans import (debug_nans, debug_nans_enabled,
                                       first_nan, nan_probe,
                                       reset_nan_state)
from apex_tpu.trace.podview import (ClockAlignment, CollectiveSkew,
                                    PodSpan, PodTimeline, RankClock,
                                    RankTimeline, align_clocks,
                                    load_span_events)
from apex_tpu.trace.recorder import FlightRecorder, StepRecord, rank_path
from apex_tpu.trace.spans import (SpanEvent, StepTimeline, StepTrace,
                                  Tracer, current_tracer, span, step)
from apex_tpu.trace.straggler import (HeartbeatWriter, StragglerDetector,
                                      StragglerReport, StragglerWatch,
                                      read_heartbeats)
from apex_tpu.trace.watchdog import HangWatchdog

__all__ = [
    "span", "step", "Tracer", "SpanEvent", "StepTrace", "StepTimeline",
    "current_tracer",
    "FlightRecorder", "StepRecord", "rank_path",
    "HangWatchdog",
    "HeartbeatWriter", "StragglerDetector", "StragglerReport",
    "StragglerWatch", "read_heartbeats",
    "PodSpan", "PodTimeline", "RankTimeline", "RankClock",
    "ClockAlignment", "CollectiveSkew", "align_clocks",
    "load_span_events",
    "debug_nans", "debug_nans_enabled", "nan_probe", "first_nan",
    "reset_nan_state",
]
