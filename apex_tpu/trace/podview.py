"""Pod observatory: merge N ranks' span timelines into one pod view.

Every other telemetry layer is per-rank: the goodput ledger attributes
one process's wall clock, the flight recorder dumps one rank's ring,
and the straggler detector sees only heartbeat lag. This module joins
the ranks' ``kind="span"`` JSONL streams (each on its own arbitrary
``perf_counter`` origin) into one :class:`PodTimeline` and answers the
two questions a per-rank view structurally cannot:

- **who made the pod wait** — for every collective instance, how much
  of its time was *wait-for-laggard* (entry skew, charged to the last
  arriver and the host span it was running) versus *wire time*
  (last-entry → exit);
- **is the link model stale** — the measured wire times are the join
  key :mod:`apex_tpu.monitor.comm_drift` compares against
  :meth:`apex_tpu.parallel.CommPlan.hop_seconds`.

**Clock alignment contract.** Ranks share no clock; what they share is
that a blocking collective's *exit* is simultaneous across its
participants up to the collective latency α. Collective spans are
matched across ranks by ``(step, name, occurrence-within-step)`` —
stable under out-of-order arrival because occurrences are renumbered in
local-time order — and the per-rank offsets minimize the squared
spread of matched exit times (:func:`align_clocks`): a bipartite least
squares solved by alternating the consensus exit per collective and
the offset per rank, gauged so the reference rank's offset is zero.
``fit_drift=True`` additionally fits a per-rank linear clock *rate*
term (crystals on different hosts genuinely tick at slightly different
rates over a long run). A rank that shares no collective with the rest
cannot be aligned — it merges at offset 0 with ``aligned=False``
rather than silently pretending; a single-rank merge is the degenerate
identity. The residual RMS per rank states how well the model fits —
on a real pod it is bounded below by α, so treat sub-α blame deltas
as noise.

**Blame semantics.** For one matched collective instance, on the
aligned clock: ``skew_ms = last entry − first entry`` (the pod-wide
wait the laggard caused), ``wire_ms = exit − last entry`` (the time
the fabric actually took once everyone arrived). The blame lands on
the last-arriving rank AND the deepest non-collective span that rank
was still running when the others were already waiting — "rank 2 held
bucket00/dcn for 40 ms finishing ``data/load``" is actionable, "the
collective was slow" is not. :meth:`PodTimeline.critical_path` chains
those records per step: the sequence of (laggard rank, blamed span)
waits plus wire segments that actually determined step wall time.

Outputs: merged Perfetto-loadable Chrome trace with per-rank
``process_name`` metadata (:meth:`PodTimeline.chrome_trace`),
``kind="pod_align"`` / ``kind="pod_skew"`` events for the ``podview``
metrics channel (``MetricsLogger(podview_sink=...)``;
``scripts/check_metrics_schema.py --kind podview`` validates), and
per-(rank, step) skew milliseconds for the goodput ledger's
``comm_skew``/``comm_wire`` split
(:meth:`PodTimeline.rank_step_skew` →
:meth:`apex_tpu.monitor.GoodputLedger.note_pod_skew`). The CI gate is
``scripts/pod_audit.py --cpu8``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["PodSpan", "RankTimeline", "RankClock", "ClockAlignment",
           "CollectiveSkew", "PodTimeline", "align_clocks",
           "load_span_events"]


class PodSpan:
    """One span occurrence on one rank, on that rank's LOCAL clock
    (milliseconds since its tracer's origin) until aligned."""

    __slots__ = ("name", "kind", "step", "rank", "t_ms", "dur_ms",
                 "depth", "aborted")

    def __init__(self, name: str, kind: str, step: Optional[int],
                 rank: int, t_ms: float, dur_ms: float, depth: int = 0,
                 aborted: bool = False):
        self.name = name
        self.kind = kind
        self.step = step
        self.rank = rank
        self.t_ms = t_ms
        self.dur_ms = dur_ms
        self.depth = depth
        self.aborted = aborted

    @property
    def end_ms(self) -> float:
        return self.t_ms + self.dur_ms

    @classmethod
    def from_event(cls, ev: Dict) -> "PodSpan":
        return cls(name=ev["name"], kind=ev.get("span_kind", "span"),
                   step=ev.get("step"), rank=int(ev.get("rank", 0)),
                   t_ms=float(ev["t_ms"]), dur_ms=float(ev["dur_ms"]),
                   depth=int(ev.get("depth", 0)),
                   aborted=bool(ev.get("aborted", False)))


def load_span_events(events: Iterable) -> Dict[int, "RankTimeline"]:
    """``{rank: RankTimeline}`` from a mixed event stream — dicts
    (``kind="span"`` kept, everything else skipped), JSON lines, or an
    open file. The one loader the audit and offline tooling share."""
    per: Dict[int, List[PodSpan]] = {}
    for ev in events:
        if isinstance(ev, str):
            ev = ev.strip()
            if not ev:
                continue
            try:
                ev = json.loads(ev)
            except ValueError:
                continue          # torn tail of a live append
        if not isinstance(ev, dict) or ev.get("kind") != "span":
            continue
        s = PodSpan.from_event(ev)
        per.setdefault(s.rank, []).append(s)
    return {r: RankTimeline(r, spans) for r, spans in per.items()}


class RankTimeline:
    """One rank's spans, sorted into local-time order (out-of-order
    arrival — a late-flushed JSONL segment — is harmless: matching
    keys on occurrence index within the sorted order)."""

    def __init__(self, rank: int, spans: Sequence[PodSpan]):
        self.rank = rank
        self.spans: List[PodSpan] = sorted(
            spans, key=lambda s: (s.step if s.step is not None else -1,
                                  s.t_ms))

    def collectives(self) -> Dict[Tuple, PodSpan]:
        """``{(step, name, occurrence): span}`` over the completed
        ``kind="collective"`` spans — the cross-rank match keys."""
        out: Dict[Tuple, PodSpan] = {}
        counts: Dict[Tuple, int] = {}
        for s in self.spans:
            if s.kind != "collective" or s.aborted:
                continue
            base = (s.step, s.name)
            occ = counts.get(base, 0)
            counts[base] = occ + 1
            out[(s.step, s.name, occ)] = s
        return out


@dataclasses.dataclass
class RankClock:
    """One rank's clock model: ``aligned(t) = t + offset_ms +
    drift · (t − t_ref_ms)``."""

    rank: int
    offset_ms: float = 0.0
    drift: float = 0.0            # dimensionless rate error (s/s)
    t_ref_ms: float = 0.0
    residual_ms: Optional[float] = None  # RMS misfit over its matches
    n_shared: int = 0             # matched collective instances
    aligned: bool = False

    def align(self, t_ms: float) -> float:
        return t_ms + self.offset_ms + self.drift * (t_ms - self.t_ref_ms)


class ClockAlignment:
    """The fitted per-rank clock models + the reference-rank gauge."""

    def __init__(self, clocks: Dict[int, RankClock], reference: int):
        self.clocks = clocks
        self.reference = reference

    def align(self, rank: int, t_ms: float) -> float:
        clock = self.clocks.get(rank)
        return t_ms if clock is None else clock.align(t_ms)

    def to_events(self, wall_time: Optional[float] = None) -> List[Dict]:
        """One ``kind="pod_align"`` event per rank (podview channel)."""
        wt = time.time() if wall_time is None else wall_time
        out = []
        for r in sorted(self.clocks):
            c = self.clocks[r]
            out.append({
                "kind": "pod_align", "rank": r,
                "offset_ms": round(c.offset_ms, 4),
                "drift_ppm": round(c.drift * 1e6, 4),
                "residual_ms": (round(c.residual_ms, 4)
                                if c.residual_ms is not None else None),
                "n_shared": c.n_shared, "aligned": c.aligned,
                "reference": self.reference, "wall_time": wt})
        return out


def _fit_rank(points: List[Tuple[float, float]], t_ref: float,
              fit_drift: bool) -> Tuple[float, float]:
    """(offset, drift) minimizing Σ (offset + drift·(e−t_ref) − y)²
    over points (e, y). Closed form; drift needs ≥ 3 points spanning
    some time (a degenerate spread falls back to offset-only)."""
    n = len(points)
    ys = [y for _, y in points]
    if not fit_drift or n < 3:
        return sum(ys) / n, 0.0
    xs = [e - t_ref for e, _ in points]
    mx, my = sum(xs) / n, sum(ys) / n
    sxx = sum((x - mx) ** 2 for x in xs)
    if sxx < 1e-9:
        return my, 0.0
    sxy = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    drift = sxy / sxx
    return my - drift * mx, drift


def align_clocks(timelines: Dict[int, RankTimeline], *,
                 reference: Optional[int] = None,
                 fit_drift: bool = False, iters: int = 60,
                 tol_ms: float = 1e-7) -> ClockAlignment:
    """Fit per-rank clock offsets (and optional drift) from shared
    collective exits. See the module docstring for the contract; the
    solver is alternating least squares — exact for the offset-only
    bipartite problem, and the drift refit reuses the same loop."""
    coll = {r: tl.collectives() for r, tl in timelines.items()}
    # keys observed on >= 2 ranks constrain the fit; exits per key
    shared: Dict[Tuple, Dict[int, float]] = {}
    for r, per in coll.items():
        for key, s in per.items():
            shared.setdefault(key, {})[r] = s.end_ms
    shared = {k: v for k, v in shared.items() if len(v) >= 2}

    n_shared = {r: sum(1 for v in shared.values() if r in v)
                for r in timelines}
    constrained = [r for r in sorted(timelines) if n_shared[r] > 0]
    if reference is None:
        reference = (constrained[0] if constrained
                     else min(timelines) if timelines else 0)
    all_exits = [e for v in shared.values() for e in v.values()]
    t_ref = sum(all_exits) / len(all_exits) if all_exits else 0.0

    clocks = {r: RankClock(rank=r, t_ref_ms=t_ref,
                           n_shared=n_shared.get(r, 0))
              for r in timelines}
    for _ in range(max(int(iters), 1)):
        consensus = {key: sum(clocks[r].align(e) for r, e in v.items())
                     / len(v) for key, v in shared.items()}
        worst = 0.0
        for r in constrained:
            pts = [(e, consensus[key] - e)
                   for key, v in shared.items()
                   for rr, e in v.items() if rr == r]
            off, drift = _fit_rank(pts, t_ref, fit_drift)
            worst = max(worst, abs(off - clocks[r].offset_ms))
            clocks[r].offset_ms, clocks[r].drift = off, drift
        # gauge: the reference rank's model is the identity (without
        # this the whole pod's clock floats freely between iterations)
        ref = clocks[reference]
        g_off, g_drift = ref.offset_ms, ref.drift
        for r in constrained:
            c = clocks[r]
            c.offset_ms -= g_off
            c.drift -= g_drift
        if worst < tol_ms:
            break

    consensus = {key: sum(clocks[r].align(e) for r, e in v.items())
                 / len(v) for key, v in shared.items()}
    for r, c in clocks.items():
        res = [(consensus[key] - c.align(e)) ** 2
               for key, v in shared.items()
               for rr, e in v.items() if rr == r]
        if res:
            c.residual_ms = (sum(res) / len(res)) ** 0.5
        # the reference is aligned by definition (single-rank merges
        # included); everyone else needs at least one shared collective
        c.aligned = (r == reference) or c.n_shared > 0
    return ClockAlignment(clocks, reference)


@dataclasses.dataclass
class CollectiveSkew:
    """One matched collective instance, split on the aligned clock:
    wait-for-laggard (``skew_ms``, blamed) vs wire (``wire_ms``)."""

    step: Optional[int]
    name: str
    occurrence: int
    n_ranks: int
    entries: Dict[int, float]     # {rank: aligned entry ms}
    exit_ms: float                # aligned consensus exit
    skew_ms: float                # last entry − first entry
    wire_ms: float                # exit − last entry (clamped ≥ 0)
    blamed_rank: Optional[int]    # the last arriver
    blamed_span: Optional[str]    # what it was running meanwhile

    def to_event(self, wall_time: Optional[float] = None) -> Dict:
        return {"kind": "pod_skew", "step": self.step, "name": self.name,
                "occurrence": self.occurrence, "n_ranks": self.n_ranks,
                "skew_ms": round(self.skew_ms, 4),
                "wire_ms": round(self.wire_ms, 4),
                "blamed_rank": self.blamed_rank,
                "blamed_span": self.blamed_span,
                "wall_time": (time.time() if wall_time is None
                              else wall_time)}


class PodTimeline:
    """N ranks' span timelines on one aligned clock.

    Build with :meth:`merge` from the ranks' ``kind="span"`` event
    streams (``Tracer.span_events`` per rank, however they were
    shipped). Everything downstream — skew blame, critical path, the
    merged Chrome trace, the podview events — reads aligned times.
    """

    def __init__(self, timelines: Dict[int, RankTimeline],
                 alignment: ClockAlignment):
        self.timelines = timelines
        self.alignment = alignment
        self.ranks = sorted(timelines)

    @classmethod
    def merge(cls, events, *, reference: Optional[int] = None,
              fit_drift: bool = False) -> "PodTimeline":
        """Merge a flat event iterable (or ``{rank: events}`` dict)
        into one aligned timeline."""
        if isinstance(events, dict):
            flat: List = []
            for evs in events.values():
                flat.extend(evs)
            events = flat
        timelines = load_span_events(events)
        return cls(timelines, align_clocks(timelines,
                                           reference=reference,
                                           fit_drift=fit_drift))

    def aligned(self, span: PodSpan) -> Tuple[float, float]:
        """(start_ms, end_ms) of one span on the pod clock."""
        a = self.alignment
        return (a.align(span.rank, span.t_ms),
                a.align(span.rank, span.end_ms))

    # -- blame ----------------------------------------------------------------

    def _blame_span(self, rank: int, step: Optional[int],
                    lo: float, hi: float) -> Optional[str]:
        """The deepest non-collective span ``rank`` was running inside
        the wait window [lo, hi) — what the pod was actually waiting
        on. Ties go to the latest-started (the innermost entered)."""
        tl = self.timelines.get(rank)
        if tl is None or hi <= lo:
            return None
        best, best_key = None, None
        for s in tl.spans:
            if s.step != step or s.kind == "collective":
                continue
            t0, t1 = self.aligned(s)
            if t0 < hi and t1 > lo:
                key = (s.depth, t0)
                if best_key is None or key > best_key:
                    best, best_key = s.name, key
        return best

    def collective_skew(self) -> List[CollectiveSkew]:
        """Every matched collective instance's skew/wire split, in
        aligned-time order."""
        shared: Dict[Tuple, Dict[int, PodSpan]] = {}
        for r, tl in self.timelines.items():
            for key, s in tl.collectives().items():
                shared.setdefault(key, {})[r] = s
        out: List[CollectiveSkew] = []
        for key, per in shared.items():
            if len(per) < 2:
                continue
            step, name, occ = key
            entries = {r: self.aligned(s)[0] for r, s in per.items()}
            exits = [self.aligned(s)[1] for s in per.values()]
            exit_ms = sum(exits) / len(exits)
            first = min(entries.values())
            last_rank = max(entries, key=entries.get)
            last = entries[last_rank]
            out.append(CollectiveSkew(
                step=step, name=name, occurrence=occ, n_ranks=len(per),
                entries=entries, exit_ms=exit_ms,
                skew_ms=last - first,
                wire_ms=max(exit_ms - last, 0.0),
                blamed_rank=last_rank,
                blamed_span=self._blame_span(last_rank, step,
                                             first, last)))
        out.sort(key=lambda c: (c.step if c.step is not None else -1,
                                min(c.entries.values())))
        return out

    def rank_step_skew(self) -> Dict[Tuple[int, Optional[int]], float]:
        """``{(rank, step): ms}`` each rank spent waiting for laggards
        inside collectives — per collective, rank r waited
        ``last_entry − entry_r``. This is the pod-measured join the
        goodput ledger's ``comm_wire → comm_skew`` move consumes
        (:meth:`apex_tpu.monitor.GoodputLedger.note_pod_skew`)."""
        out: Dict[Tuple[int, Optional[int]], float] = {}
        for c in self.collective_skew():
            last = max(c.entries.values())
            for r, entry in c.entries.items():
                wait = last - entry
                if wait > 0:
                    k = (r, c.step)
                    out[k] = out.get(k, 0.0) + wait
        return out

    def critical_path(self, step: Optional[int] = None) -> List[Dict]:
        """The per-step cross-rank critical chain: collectives in
        aligned order, each contributing its wire segment plus the
        wait segment charged to (laggard rank, blamed span). The
        chain's segments are what actually determined step wall time —
        compute that overlapped another rank's wait never appears."""
        segs: List[Dict] = []
        for c in self.collective_skew():
            if step is not None and c.step != step:
                continue
            if c.skew_ms > 0:
                segs.append({"segment": "wait", "step": c.step,
                             "collective": c.name,
                             "occurrence": c.occurrence,
                             "rank": c.blamed_rank,
                             "span": c.blamed_span,
                             "dur_ms": round(c.skew_ms, 4)})
            segs.append({"segment": "wire", "step": c.step,
                         "collective": c.name,
                         "occurrence": c.occurrence,
                         "rank": None, "span": None,
                         "dur_ms": round(c.wire_ms, 4)})
        return segs

    # -- exports --------------------------------------------------------------

    def to_events(self, wall_time: Optional[float] = None) -> List[Dict]:
        """``pod_align`` + ``pod_skew`` events for the podview channel
        (``MetricsLogger(podview_sink=...).record_podview``)."""
        wt = time.time() if wall_time is None else wall_time
        return (self.alignment.to_events(wall_time=wt)
                + [c.to_event(wall_time=wt)
                   for c in self.collective_skew()])

    def chrome_trace(self) -> Dict:
        """One merged Chrome-trace dict, all ranks on the aligned
        clock, with per-rank ``process_name``/``process_sort_index``
        metadata so Perfetto renders labeled "rank N" tracks instead
        of anonymous colliding pids."""
        events: List[Dict] = []
        for r in self.ranks:
            clock = self.alignment.clocks.get(r)
            label = f"rank {r}" if clock is None or clock.aligned \
                else f"rank {r} (unaligned)"
            events += [
                {"name": "process_name", "ph": "M", "pid": r, "tid": 0,
                 "args": {"name": label}},
                {"name": "process_sort_index", "ph": "M", "pid": r,
                 "tid": 0, "args": {"sort_index": r}},
            ]
            for s in self.timelines[r].spans:
                t0, _ = self.aligned(s)
                events.append({
                    "name": s.name, "ph": "X", "cat": s.kind,
                    "ts": t0 * 1e3, "dur": s.dur_ms * 1e3,
                    "pid": r, "tid": 1 + s.depth,
                    "args": {"step": s.step}})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": {"producer": "apex_tpu.trace.podview",
                             "reference_rank": self.alignment.reference,
                             "ranks": self.ranks}}

    def write_chrome_trace(self, path: str) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path
