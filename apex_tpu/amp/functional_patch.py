"""Reversible jnp/lax entry-point patching — O1 coverage for raw ops.

The reference's O1 wraps every function in the torch namespaces
(`apex/amp/amp.py:68-177`, `apex/amp/wrap.py:10-113`), so user code
calling ``torch.matmul`` directly — not through an ``nn.Module`` — still
gets the cast policy. The flax interceptor (amp/interceptor.py) covers
module calls only; this module covers the rest: inside ``auto_cast``,
the *user-facing* MXU entry points (``jnp.einsum``/``matmul``/``dot``/
``tensordot`` and the ``lax.conv*`` family) cast floating inputs to the
policy half dtype, and the numerically-sensitive entry points
(``jax.nn.softmax``/``log_softmax``) cast to fp32 — mirroring the
whitelist/blacklist split of `lists/torch_overrides.py:7-117`.

Precedence rules (the reference's "user wrapper wins" ordering):

- ``lax.dot_general`` is NOT patched: it is the lowering target of
  every dense op — flax modules and Pallas kernel bodies (whose fp32
  accumulators must not be downcast) both route through it.
- Calls *inside an interceptor-classified module* are exempt via
  :func:`suspend`: once the interceptor has applied the policy to a
  module call (including honoring an explicit user ``dtype=``), the
  raw-op patch must not second-guess the dtypes its body computes in.
  Library fp32 oracles (e.g. ``attention_reference``) use the same
  escape hatch.
- Nested ``auto_cast`` contexts push their policy on a stack; the
  innermost policy's half dtype applies (patches are installed once,
  reference-counted, and fully restored on the outermost exit — pinned
  by tests/test_amp_api.py::test_functional_patch_restores).
"""

from __future__ import annotations

import contextlib
import functools
import threading

import jax
import jax.numpy as jnp

from apex_tpu.utils import tree_cast

# (module, attr) pairs wrapped to the HALF policy — the O1 whitelist
# surface for raw calls (`torch_overrides.py` MM_FNS/CONV_FNS analogue)
_HALF_TARGETS = (
    (jnp, "einsum"),
    (jnp, "matmul"),
    (jnp, "dot"),
    (jnp, "vdot"),
    (jnp, "inner"),
    (jnp, "tensordot"),
    (jax.lax, "conv"),
    (jax.lax, "conv_general_dilated"),
    (jax.lax, "conv_with_general_padding"),
    (jax.lax, "conv_transpose"),
)

# wrapped to fp32 — blacklist surface (`functional_overrides.py:30-60`)
_FLOAT_TARGETS = (
    (jax.nn, "softmax"),
    (jax.nn, "log_softmax"),
)

# user-registered raw targets (the reference lets users register *any*
# function for O1 treatment, `apex/amp/amp.py:30-64`; the built-in
# tuples above are the fixed surface, these extend it at runtime via
# ``amp.register_half_op((module, attr))`` — see lists.register_half_op)
_USER_HALF_TARGETS: list = []
_USER_FLOAT_TARGETS: list = []


def register_raw_target(module, attr: str, kind: str) -> None:
    """Register a user-owned ``module.attr`` callable for the raw-op O1
    treatment ('half' or 'float'). Takes effect immediately if an
    ``auto_cast`` scope is active, and on every subsequent scope.
    Re-registering with the other kind moves the target."""
    if kind not in ("half", "float"):
        raise ValueError(f"kind must be 'half' or 'float', got {kind!r}")
    fn = getattr(module, attr)
    if not callable(fn):
        raise TypeError(f"{attr!r} on {module!r} is not callable")
    key = (module, attr)
    with _lock:
        for lst in (_USER_HALF_TARGETS, _USER_FLOAT_TARGETS):
            if key in lst:
                lst.remove(key)
        (_USER_HALF_TARGETS if kind == "half"
         else _USER_FLOAT_TARGETS).append(key)
        if _patch_count > 0:
            # live scope: (re)wrap now. A target may appear in
            # _originals more than once (user target overlapping a
            # built-in): restore the FIRST-pushed entry — the true
            # original — and drop every record, so wrappers never stack
            # or leak past the scope exit.
            matches = [i for i, (mod, name, _) in enumerate(_originals)
                       if (mod, name) == key]
            if matches:
                setattr(module, attr, _originals[matches[0]][2])
                for i in reversed(matches):
                    del _originals[i]
            orig = getattr(module, attr)
            _originals.append((module, attr, orig))
            wrap = _wrap_half if kind == "half" else _wrap_float
            setattr(module, attr, wrap(orig))

_lock = threading.Lock()
_patch_count = 0             # processwide: are the setattr patches in?
_originals: list = []
_tls = threading.local()     # per-thread: suspend depth + policy stack
# The policy stack is THREAD-local while the attribute patches are
# process-global: a thread that never entered auto_cast sees an empty
# stack and gets passthrough behavior, so a concurrent eval/checkpoint
# thread is never downcast by another thread's context.


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def _suspended() -> bool:
    return getattr(_tls, "depth", 0) > 0


@contextlib.contextmanager
def suspend():
    """Run with the raw-op patches inert (module bodies whose precision
    the interceptor already decided; library fp32 oracles)."""
    _tls.depth = getattr(_tls, "depth", 0) + 1
    try:
        yield
    finally:
        _tls.depth -= 1


def _wrap_half(fn):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        stack = _stack()
        if _suspended() or not stack:
            return fn(*args, **kwargs)
        dt = stack[-1]
        return fn(*tree_cast(args, dt), **tree_cast(kwargs, dt))
    wrapped.__wrapped_by_apex_tpu__ = True
    return wrapped


def _wrap_float(fn):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if _suspended() or not _stack():
            return fn(*args, **kwargs)
        return fn(*tree_cast(args, jnp.float32),
                  **tree_cast(kwargs, jnp.float32))
    wrapped.__wrapped_by_apex_tpu__ = True
    return wrapped


def unregister_raw_target(module, attr: str) -> None:
    """Remove a user-registered raw target (inverse of
    :func:`register_raw_target`). If a scope is live, the user wrapper
    is unwound immediately; future scopes no longer wrap it. Unknown
    targets are ignored (idempotent), and built-in patch surface is
    never stripped — a target that overlaps a built-in list reverts to
    the built-in treatment, not to the raw function."""
    key = (module, attr)
    with _lock:
        was_registered = False
        for lst in (_USER_HALF_TARGETS, _USER_FLOAT_TARGETS):
            if key in lst:
                lst.remove(key)
                was_registered = True
        if not was_registered or _patch_count == 0:
            return
        matches = [i for i, (mod, name, _) in enumerate(_originals)
                   if (mod, name) == key]
        if not matches:
            return
        orig = _originals[matches[0]][2]
        for i in reversed(matches):
            del _originals[i]
        setattr(module, attr, orig)
        # overlapping built-in target: re-install ITS wrapper so the
        # scope's built-in O1 surface survives the user unregistration
        for targets, wrap in ((_HALF_TARGETS, _wrap_half),
                              (_FLOAT_TARGETS, _wrap_float)):
            if key in targets:
                _originals.append((module, attr, orig))
                setattr(module, attr, wrap(orig))
                break


def patch_functional(policy) -> None:
    """Install the raw-op casts for ``policy`` (nested contexts push the
    policy; call :func:`unpatch_functional` symmetrically)."""
    global _patch_count
    _stack().append(jnp.dtype(policy.half_dtype))
    with _lock:
        _patch_count += 1
        if _patch_count > 1:
            return
        seen = set()
        # user registrations out-prioritize the built-ins (the
        # reference's "user wrapper wins"): wrap each target once
        for targets, wrap in (
                (_USER_HALF_TARGETS, _wrap_half),
                (_USER_FLOAT_TARGETS, _wrap_float),
                (_HALF_TARGETS, _wrap_half),
                (_FLOAT_TARGETS, _wrap_float)):
            for mod, name in targets:
                if (id(mod), name) in seen:
                    continue
                seen.add((id(mod), name))
                orig = getattr(mod, name)
                _originals.append((mod, name, orig))
                setattr(mod, name, wrap(orig))


def unpatch_functional() -> None:
    global _patch_count
    s = _stack()
    if s:
        s.pop()
    with _lock:
        if _patch_count == 0:
            return
        _patch_count -= 1
        if _patch_count:
            return
        while _originals:
            mod, name, orig = _originals.pop()
            setattr(mod, name, orig)
