"""Automatic mixed precision for *unmodified* flax models (O1 ergonomics).

The reference achieves "user model unchanged" by monkey-patching the torch
namespaces (`apex/amp/amp.py:68-177`) — interpreter-global mutation that has
no TPU-idiomatic analogue. In JAX the equivalent interception point is flax's
method interceptor stack: :func:`auto_cast` installs an interceptor that, for
the duration of a trace, (a) casts floating inputs of MXU-bound modules
(Dense/Conv/Attention/...) to the policy's half dtype and precision-sensitive
modules (norms) to fp32, and (b) retargets each intercepted module's
``dtype`` attribute so flax's internal ``promote_dtype`` computes in the
policy dtype rather than re-promoting to fp32 against fp32 params.

Because interception happens at trace time under ``jax.jit``, the per-call
wrapper cost the reference pays in eager mode (cast cache, dict lookups —
`apex/amp/utils.py:77-123`) is compiled away entirely.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from apex_tpu.amp import lists
from apex_tpu.amp.policy import Policy, policy_scope
from apex_tpu.utils import tree_cast


def make_interceptor(policy: Policy):
    """Build a flax interceptor applying ``policy``'s op cast tables.

    Classification order mirrors the reference rule that user
    registrations out-prioritise the built-in lists
    (`apex/amp/amp.py:94-114`): user float registry, user half registry
    (``lists.register_float_module`` / ``register_half_module``), then the
    built-in norm blacklist, then the MXU whitelist.
    """
    import flax.linen as nn

    half_mods, float_mods = lists._flax_module_tables()
    user_half = tuple(lists._EXTRA_HALF_MODULES)
    user_float = tuple(lists._EXTRA_FLOAT_MODULES)
    half = jnp.dtype(policy.half_dtype)

    def interceptor(next_fun, args, kwargs, context):
        if not policy.enabled:
            return next_fun(*args, **kwargs)
        mod = context.module
        if context.method_name != "__call__":
            return next_fun(*args, **kwargs)
        if isinstance(mod, user_float):
            target = jnp.float32
        elif isinstance(mod, user_half):
            target = half
        elif isinstance(mod, float_mods):
            # blacklist: norms/statistics in fp32
            target = jnp.float32
        elif isinstance(mod, half_mods):
            # whitelist: MXU ops in half
            target = half
        else:
            return next_fun(*args, **kwargs)
        args = tree_cast(args, target)
        kwargs = tree_cast(kwargs, target)
        retargeted = _retarget_dtype(mod, target)
        # precision for this call is decided here (incl. an explicit
        # user dtype=, which is never retargeted) — the O1 raw-op patch
        # must not second-guess the module body's internal casts
        from apex_tpu.amp import functional_patch
        try:
            with functional_patch.suspend():
                return next_fun(*args, **kwargs)
        finally:
            if retargeted:
                object.__setattr__(mod, "dtype", None)

    return interceptor


def _retarget_dtype(mod, dtype) -> bool:
    """Point ``mod.dtype`` at the policy dtype for this call.

    flax modules are frozen dataclasses, but ``dtype`` is a plain field read
    at call time by ``promote_dtype`` — retargeting it on the live instance
    (the same escape hatch flax itself uses for internal state) makes the
    module compute in ``dtype`` while its params stay in ``param_dtype``.
    Only touched when the user left ``dtype=None`` (the flax default), so an
    explicit user choice always wins. Returns whether a retarget happened;
    the caller restores ``dtype=None`` after the call so a module instance
    reused outside :func:`auto_cast` is unaffected.
    """
    if hasattr(mod, "dtype") and getattr(mod, "dtype") is None:
        object.__setattr__(mod, "dtype", dtype)
        return True
    return False


@contextlib.contextmanager
def auto_cast(policy: Policy):
    """Context manager enabling automatic per-module casting for flax models.

    Usage::

        with amp.auto_cast(policy):
            logits = model.apply(variables, x)

    Also binds ``policy`` as the ambient policy for ``apex_tpu.ops``, and
    — when ``policy.patch_ops`` (O1) — reversibly patches the raw
    ``jnp``/``lax`` MXU entry points so user code calling ``jnp.einsum``
    etc. directly gets half-precision GEMMs too (the torch-namespace
    analogue; see amp/functional_patch.py for the exact surface and the
    deliberate ``lax.dot_general`` exclusion).
    """
    import flax.linen as nn

    from apex_tpu.amp import functional_patch

    do_patch = policy.enabled and policy.patch_ops
    with policy_scope(policy):
        with nn.intercept_methods(make_interceptor(policy)):
            if do_patch:
                functional_patch.patch_functional(policy)
            try:
                yield
            finally:
                if do_patch:
                    functional_patch.unpatch_functional()
