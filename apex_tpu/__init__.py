"""apex_tpu — a TPU-native mixed-precision & distributed training framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of NVIDIA Apex
(reference: /root/reference, see SURVEY.md):

- ``apex_tpu.amp``       — precision policy engine with O0–O3 presets and a
                           functional dynamic loss scaler (no host syncs).
- ``apex_tpu.arena``     — flat parameter arena (the multi-tensor-apply substrate).
- ``apex_tpu.ops``       — fused Pallas kernels: multi-tensor scale/axpby/l2norm,
                           LayerNorm, MLP, softmax-CE, NHWC BatchNorm, attention.
- ``apex_tpu.optim``     — fused optimizers (SGD/Adam/LAMB/NovoGrad/Adagrad) and
                           ZeRO-style sharded distributed optimizers.
- ``apex_tpu.parallel``  — data parallelism, SyncBatchNorm, LARC, mesh helpers,
                           ring-attention sequence parallelism.
- ``apex_tpu.models``    — ResNet, DCGAN, BERT-style transformer, RNN stacks.
- ``apex_tpu.sparsity``  — 2:4 structured sparsity (ASP).
- ``apex_tpu.prof``      — profiler/trace tooling over jax.profiler + HLO cost
                           analysis.
- ``apex_tpu.monitor``   — runtime telemetry: in-graph training-health
                           counters + host-side metrics pipeline (sinks,
                           step-time/MFU, collective-bytes accounting).
- ``apex_tpu.trace``     — distributed tracing + flight recorder: span-level
                           step timelines (Chrome-trace/Perfetto export),
                           crash dumps, hang watchdog, NaN provenance.
- ``apex_tpu.lint``      — apexlint: jaxpr/HLO static-analysis passes that
                           catch precision leaks, donation misses, implicit
                           resharding and host syncs before they cost a run.
- ``apex_tpu.ckpt``      — elastic checkpointing + fault escalation: async
                           donation-safe sharded snapshots, crash-safe
                           manifest-last commits, resume on a different
                           mesh shape, silent-rank → checkpoint-and-exit.
- ``apex_tpu.guard``     — self-healing training: in-graph anomaly
                           detection (loss spikes, grad explosions,
                           nonfinite params), a skip→backoff→rewind→
                           escalate policy ladder, and a deterministic
                           chaos-injection harness.

Unlike the reference (an interception-based library over an eager framework),
apex_tpu expresses the same capabilities as *policies, functional transforms and
kernels* compiled by XLA: precision is a policy object applied at the library
boundary, loss scaling is explicit state threaded through the train step,
gradient synchronisation is ``psum`` over a named mesh axis, and the fused
CUDA kernels of the reference are Pallas kernels over a flat parameter arena.
"""

__version__ = "0.1.0"

from apex_tpu import _compat  # noqa: F401  (installs jax API shims first)
from apex_tpu import amp
from apex_tpu import arena
from apex_tpu import ckpt
from apex_tpu import fp16_utils
from apex_tpu import guard
from apex_tpu import lint
from apex_tpu import monitor
from apex_tpu import ops
from apex_tpu import optim
from apex_tpu import parallel
from apex_tpu import prof
from apex_tpu import reparam
from apex_tpu import trace
from apex_tpu import utils

__all__ = ["amp", "arena", "ckpt", "fp16_utils", "guard", "lint",
           "monitor", "ops", "optim", "parallel", "prof", "reparam",
           "trace", "utils", "__version__"]
