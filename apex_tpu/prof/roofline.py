"""Per-op roofline attribution: measured time vs attainable time.

PERF.md has been closing this loop by hand for five rounds: join each
hot op's *measured* device time (xplane trace) with its *analytic* cost
(FLOPs + bytes from the optimized HLO), price it against the chip's
peaks (MXU FLOP/s, HBM bytes/s), and the ops whose measured time sits
above their attainable bound are the remaining MFU points. This module
is that ledger as a tool:

    report = prof.roofline_report(compiled, profile)
    print(report.table())
    for gap in report.worst_gaps(5): ...   # the autotuner's candidates

- **analytic side** — per top-level instruction of the optimized HLO:
  dot/conv FLOPs (including FLOPs of the fused computation a ``fusion``
  calls, attributed to the calling instruction — the unit the device
  actually times), HBM bytes = operand + result bytes of the top-level
  op (fused temps live in registers/VMEM), attention-kernel FLOPs for
  ``tpu_custom_call`` ops recognized by scope (4·B·H·S²·D forward,
  10·B·H·S²·D backward, with the d<128 lane-cap on the attainable MXU
  rate — the d=64 cap PERF.md's BERT ledger prices by hand);
- **measured side** — a :class:`~apex_tpu.prof.xplane.TraceProfile`
  (live capture on TPU, committed ``tests/fixtures/*.xplane.pb`` in
  CPU CI). Rows without a measurement (AOT-only audits) carry
  ``measured_us=None`` — classification still works, gaps don't;
- **peak table** — :data:`~apex_tpu.prof.report.PEAK_FLOPS` +
  :data:`~apex_tpu.prof.report.PEAK_HBM_BW` (spec sheets; provenance in
  docs/profiling.md#roofline). Each op classifies **compute-bound** or
  **memory-bound** by which bound is larger; ``efficiency`` =
  attainable/measured, clamped to [0, 1] (co-scheduled overlap can beat
  an isolated-op bound — see PERF.md's ResNet mega-fusions);
- **kernel families** — rows aggregate by the named-scope conventions
  the tracer already enforces (attention / layer_norm / mlp / bn_act /
  xentropy / …), and :meth:`RooflineReport.worst_gaps` emits the
  fingerprinted (family, shape, dtype) candidate list ROADMAP item 4's
  autotuner consumes — the *measured* complement of apexlint APX104's
  static tile-padding findings.

Events: ``kind="roofline"`` through ``MetricsLogger(roofline_sink=…)``;
``check_metrics_schema.py --kind roofline`` validates. The asserted CI
audit is ``scripts/roofline_audit.py --cpu8`` (attribution closure over
the committed fixtures + the sentinel replay); the perf-regression gate
over bench trajectories is :mod:`apex_tpu.prof.sentinel`.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.prof.hlo import _DTYPE_BYTES, _conv_flops, _dot_flops
from apex_tpu.prof.report import PEAK_FLOPS, PEAK_HBM_BW, lookup_peak
from apex_tpu.prof.xplane import strip_scope

__all__ = ["RooflineRow", "RooflineReport", "roofline_report",
           "classify_family", "FAMILIES", "BOUND_CLASSES"]

#: kernel families the aggregation and the autotuner key on — the five
#: fused-op families apex_tpu ships kernels for, plus the structural
#: fallbacks for everything else
FAMILIES = ("attention", "layer_norm", "mlp", "bn_act", "xentropy",
            "optimizer", "gemm", "conv", "collective", "copy", "other")

#: roofline bound classes (the schema enum)
BOUND_CLASSES = ("compute", "memory", "unknown")

# scope-substring → family, first match wins (checked against the
# lowercased stripped scope path; the named-scope conventions the
# tracer/kernels already emit — bench/prof_bert flax module paths land
# here too via their module names)
_FAMILY_PATTERNS: Tuple[Tuple[str, str], ...] = (
    ("flash_attention", "attention"),
    ("attention", "attention"),
    ("attn", "attention"),
    ("layer_norm", "layer_norm"),
    ("layernorm", "layer_norm"),
    ("fused_layer_norm", "layer_norm"),
    ("bn_relu", "bn_act"),
    ("bn_act", "bn_act"),
    ("bn_bwd", "bn_act"),
    ("batchnorm", "bn_act"),
    ("conv_bn", "bn_act"),
    ("xentropy", "xentropy"),
    ("cross_entropy", "xentropy"),
    ("softmax_xent", "xentropy"),
    ("mlp", "mlp"),
    ("dense", "mlp"),
    ("lamb", "optimizer"),
    ("adam", "optimizer"),
    ("fused_sgd", "optimizer"),
    ("apply_gradients", "optimizer"),
    ("optim", "optimizer"),
)

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_OP_NAME_RE = re.compile(r'op_name="((?:[^"\\]|\\.)*)"')
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_INSTR_RE = re.compile(
    r"^(?:ROOT )?%?(?P<n>[^ ]+) = "
    r"(?P<shape>\((?:[^()]|\([^()]*\))*\)|[^ ]+) "
    r"(?P<op>[\w-]+)\((?P<args>[^)]*)\)")
# a computation header: "%fused_computation.3 (p0: bf16[..]) -> .. {"
# or "ENTRY %main.42 (..) -> .. {"
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\(|\{)")

# result-only opcodes that never own device time / HBM traffic
_SKIP_OPS = ("parameter", "constant", "tuple", "get-tuple-element",
             "bitcast", "after-all", "partition-id", "replica-id",
             "opt-barrier")

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "all-to-all", "collective-permute",
                   "collective-broadcast", "ragged-all-to-all")


def classify_family(scope: str, opcode: str = "",
                    category: str = "") -> str:
    """Kernel family of an op from its stripped named-scope path, with
    the opcode/category as structural fallback."""
    s = (scope or "").lower()
    for pat, fam in _FAMILY_PATTERNS:
        if pat in s:
            return fam
    if opcode.startswith(_COLLECTIVE_OPS) or category == "collective":
        return "collective"
    if opcode == "dot" or category == "gemm":
        return "gemm"
    if opcode == "convolution" or category == "conv":
        return "conv"
    if opcode == "copy" or category == "copy":
        return "copy"
    return "other"


def _shape_elems_bytes(shape_text: str) -> Tuple[int, int]:
    total_e = total_b = 0
    for dt, dims in _SHAPE_RE.findall(shape_text):
        if dt not in _DTYPE_BYTES:
            continue
        elems = int(np.prod([int(d) for d in dims.split(",") if d] or [1]))
        total_e += elems
        total_b += elems * _DTYPE_BYTES[dt]
    return total_e, total_b


def _result_dtype(shape_text: str) -> str:
    m = _SHAPE_RE.search(shape_text)
    return m.group(1) if m else "?"


def _operand_names(args_text: str) -> List[str]:
    if "%" in args_text:
        return re.findall(r"%([^\s,)]+)", args_text)
    return [a.strip().split()[-1] for a in args_text.split(",")
            if a.strip()]


def _attention_call(qshape: str, scope_raw: str) -> Optional[Tuple[float,
                                                                   float]]:
    """(flops, mxu_cap) for a flash-attention ``tpu_custom_call`` given
    its q operand's HLO shape text, or None when the shape doesn't
    parse as an attention operand.

    The FLOPs of a fused attention kernel are invisible to HLO (a
    custom-call has no dot): they are reconstructed from the q operand's
    shape — (B, S, H, D) native layout or (B·H, S, D) transposed —
    as 4·B·H·S²·D forward (QKᵀ + PV) and 10·B·H·S²·D backward
    (dQ/dK/dV re-walk s and p; the 2.5× rule PERF.md's ledger uses).
    ``mxu_cap`` is min(1, D/128): a D<128 contraction fills D of the
    128 lanes, capping the attainable MXU rate — the d=64 cap that
    makes the BERT backward's ~440 µs floor, not ~220.
    """
    m = _SHAPE_RE.search(qshape or "")
    if not m:
        return None
    dims = [int(d) for d in m.group(2).split(",") if d]
    if len(dims) == 4:            # (B, S, H, D) native layout
        b, s, h, d = dims
        bh = b * h
    elif len(dims) == 3:          # (B·H, S, D) transposed wrappers
        bh, s, d = dims
    else:
        return None
    raw = scope_raw or ""
    bwd = "transpose(" in raw or "_bwd" in raw or "/bwd" in raw
    factor = 10.0 if bwd else 4.0
    flops = factor * bh * float(s) * float(s) * d
    return flops, min(1.0, d / 128.0)


def _module_costs(hlo_text: str) -> Dict[str, Dict[str, Any]]:
    """Per-entry-instruction analytic costs from optimized HLO text.

    Returns {name: {flops, bytes, opcode, shape, scope, scope_raw,
    mxu_cap, hlo}}. Walks every computation once building a module-wide
    name→shape table and per-computation dot/conv FLOP sums, then folds
    each fused computation's FLOPs into the calling entry instruction —
    the unit the profiler times.
    """
    shapes: Dict[str, str] = {}
    # (comp, name, shape, opcode, args_text, line, is_entry)
    parsed: List[Tuple[str, str, str, str, str, str, bool]] = []
    comp, in_entry = "", False
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if raw and not raw.startswith(" ") and line.endswith("{"):
            m = _COMP_RE.match(line)
            if m:
                comp, in_entry = m.group(2), bool(m.group(1))
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name = m.group("n").lstrip("%")
        shapes[name] = m.group("shape")
        # older printers (and xplane op metadata) inline operand types:
        # "fusion(bf16[64,256]{1,0} %p0, ...)" — harvest them so
        # operands resolve even without module-level definitions (the
        # committed-fixture path); real definitions win
        for sh, onm in re.findall(
                r"(\w+\[[\d,]*\][^\s]*)\s+%([^\s,)]+)", line):
            shapes.setdefault(onm, sh)
        parsed.append((comp, name, m.group("shape"), m.group("op"),
                       m.group("args"), line, in_entry))

    # per-computation dot/conv FLOPs (the fused bodies)
    comp_flops: Dict[str, float] = {}
    instr_flops: Dict[str, float] = {}
    for comp, name, shape, op, args_text, line, _entry in parsed:
        if op not in ("dot", "convolution"):
            continue
        operands = _operand_names(args_text)
        out_elems, _ = _shape_elems_bytes(shape)
        if op == "dot":
            f = _dot_flops(line, out_elems, operands, shapes)
        else:
            f = _conv_flops(line, out_elems, operands, shapes)
        instr_flops[name] = f
        comp_flops[comp] = comp_flops.get(comp, 0.0) + f

    out: Dict[str, Dict[str, Any]] = {}
    for comp, name, shape, op, args_text, line, entry in parsed:
        if not entry or op in _SKIP_OPS:
            continue
        operands = _operand_names(args_text)
        _, out_bytes = _shape_elems_bytes(shape)
        _, in_bytes = _shape_elems_bytes(
            " ".join(shapes.get(o, "") for o in operands))
        flops = instr_flops.get(name, 0.0)
        called = _CALLS_RE.search(line)
        if called:
            flops += comp_flops.get(called.group(1), 0.0)
        sm = _OP_NAME_RE.search(line)
        scope_raw = sm.group(1) if sm else ""
        mxu_cap = 1.0
        if (op == "custom-call"
                and classify_family(strip_scope(scope_raw)) == "attention"):
            # q = the first operand; its shape comes from the module
            # symbol table, or inline from the call itself (the xplane
            # metadata path, where operand types are printed in place)
            qshape = shapes.get(operands[0], "") if operands else ""
            if not _SHAPE_RE.search(qshape):
                tail = line.split(f" {op}(", 1)[-1].split(")", 1)[0]
                qshape = tail
            attn = _attention_call(qshape, scope_raw)
            if attn is not None:
                flops, mxu_cap = attn
        out[name] = {"flops": flops, "bytes": float(out_bytes + in_bytes),
                     "opcode": op, "shape": shape,
                     "scope": strip_scope(scope_raw),
                     "scope_raw": scope_raw, "mxu_cap": mxu_cap,
                     "hlo": line[:400]}
    return out


@dataclasses.dataclass
class RooflineRow:
    """One op's measured-vs-attainable verdict."""

    name: str                     # HLO instruction name
    opcode: str
    family: str                   # one of FAMILIES
    scope: str                    # stripped named-scope path
    flops: float                  # per execution
    bytes: float                  # HBM traffic per execution (bound)
    occurrences: int              # executions in the trace (0 AOT-only)
    measured_us: Optional[float]  # avg device us per execution, or None
    compute_us: float             # flops / (peak_flops * mxu_cap)
    memory_us: float              # bytes / hbm_bw
    bound: str                    # one of BOUND_CLASSES
    dtype: str                    # result dtype
    shape: str                    # result shape text
    mxu_cap: float = 1.0          # attainable-rate cap (d<128 attention)
    hlo: str = ""

    @property
    def attainable_us(self) -> float:
        """The roofline bound: max of the compute and memory floors."""
        return max(self.compute_us, self.memory_us)

    @property
    def efficiency(self) -> Optional[float]:
        """attainable/measured ∈ [0, 1]; None without a measurement or
        a bound (the schema's nullable-efficiency contract)."""
        if self.measured_us is None or self.measured_us <= 0:
            return None
        att = self.attainable_us
        if att <= 0:
            return None
        return min(1.0, att / self.measured_us)

    @property
    def gap_us(self) -> Optional[float]:
        """Total measured time above the bound across all occurrences
        (the prize for closing this op), None on AOT-only rows."""
        if self.measured_us is None or self.attainable_us <= 0:
            return None
        return max(0.0, (self.measured_us - self.attainable_us)
                   * max(self.occurrences, 1))

    @property
    def fingerprint(self) -> str:
        """Stable (family, scope, dtype, shape) key — the tuning-DB /
        waiver identity, apexlint-fingerprint style (never includes
        measured numbers, so reruns agree)."""
        dims = _SHAPE_RE.search(self.shape)
        shape = f"{dims.group(1)}[{dims.group(2)}]" if dims else self.shape
        return f"{self.family}|{self.opcode}|{self.scope}|{shape}"

    def to_event(self, rank: int = 0, step: Optional[int] = None) -> Dict:
        """``kind="roofline"`` event (``check_metrics_schema.py --kind
        roofline`` validates)."""
        return {"kind": "roofline", "rank": rank, "step": step,
                "op": self.name, "opcode": self.opcode,
                "family": self.family, "scope": self.scope,
                "bound": self.bound, "flops": self.flops,
                "bytes": self.bytes,
                "attainable_us": round(self.attainable_us, 3),
                "measured_us": (None if self.measured_us is None
                                else round(self.measured_us, 3)),
                "efficiency": (None if self.efficiency is None
                               else round(self.efficiency, 4)),
                "gap_us": (None if self.gap_us is None
                           else round(self.gap_us, 3)),
                "occurrences": self.occurrences, "dtype": self.dtype,
                "fingerprint": self.fingerprint}


def _fmt_us(v: Optional[float]) -> str:
    return "n/a" if v is None else f"{v:.1f}"


@dataclasses.dataclass
class RooflineReport:
    """Per-op roofline ledger of one profiled (or AOT-audited) step."""

    rows: List[RooflineRow]           # sorted by gap desc, then bytes
    device_kind: str
    peak_flops: float                 # 0.0 when the chip is unknown
    hbm_bw: float
    profile_total_us: float           # sum of per-op trace time
    module_total_us: float            # device time inside XLA modules
    module_runs: int

    @property
    def measured(self) -> bool:
        return any(r.measured_us is not None for r in self.rows)

    def check_closure(self, tolerance: float = 0.05
                      ) -> Tuple[bool, float]:
        """Attribution closure: the per-op times the report attributed
        must cover the trace's total device time inside XLA modules
        within ``tolerance`` (an op the join dropped = a hole in the
        ledger). (ok, relative_error); trivially ok on AOT-only
        reports."""
        attributed = sum((r.measured_us or 0.0) * max(r.occurrences, 1)
                         for r in self.rows)
        total = self.module_total_us
        if total <= 0:
            return True, 0.0
        err = abs(attributed - total) / total
        return err <= tolerance, err

    def by_family(self) -> Dict[str, Dict[str, float]]:
        """Per-family aggregate: measured/attainable us (summed over
        occurrences), flops, bytes, efficiency."""
        out: Dict[str, Dict[str, float]] = {}
        for r in self.rows:
            occ = max(r.occurrences, 1)
            agg = out.setdefault(r.family, {
                "measured_us": 0.0, "attainable_us": 0.0,
                "flops": 0.0, "bytes": 0.0, "n_ops": 0})
            agg["n_ops"] += 1
            agg["flops"] += r.flops * occ
            agg["bytes"] += r.bytes * occ
            agg["attainable_us"] += r.attainable_us * occ
            if r.measured_us is not None:
                agg["measured_us"] += r.measured_us * occ
        for agg in out.values():
            m, a = agg["measured_us"], agg["attainable_us"]
            agg["efficiency"] = (round(min(1.0, a / m), 4)
                                 if m > 0 and a > 0 else None)
        return dict(sorted(out.items(),
                           key=lambda kv: -kv[1]["measured_us"]))

    def by_scope(self, depth: int = 2) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for r in self.rows:
            occ = max(r.occurrences, 1)
            key = "/".join([p for p in r.scope.split("/") if p][:depth]) \
                or "(unscoped)"
            agg = out.setdefault(key, {"measured_us": 0.0,
                                       "attainable_us": 0.0})
            agg["attainable_us"] += r.attainable_us * occ
            if r.measured_us is not None:
                agg["measured_us"] += r.measured_us * occ
        return dict(sorted(out.items(),
                           key=lambda kv: -kv[1]["measured_us"]))

    def what_if(self, plan: Dict[str, str]) -> List[Dict[str, Any]]:
        """The what-if dtype column: attainable time per op if a
        precision-placement verdict were applied.

        ``plan`` maps a *site* (a case-insensitive substring of the
        stripped scope — the :func:`apex_tpu.monitor.numerics.site_names`
        convention) to a target format (``FORMAT_TABLE`` key like
        ``"fp8_e4m3"`` or an HLO dtype like ``"bf16"``). For every
        matching row with a priced dtype, the HBM-traffic bound scales
        by the byte ratio and the MXU bound by the spec-sheet dtype
        ladder (each halving of element width doubles the attainable
        FLOP rate — the fp8-doubles-bf16 MXU model
        docs/profiling.md#whatif states; the roofline observatory then
        *verifies* a landed kernel actually collects, ROADMAP item 5).
        Returns JSON-able rows with ``whatif_attainable_us`` and the
        per-occurrence-summed ``whatif_gain_us`` —
        :func:`apex_tpu.monitor.numerics.placement_advisor` ranks them
        by gain × numeric safety."""
        fmt_bytes = {"fp8_e4m3": 1, "fp8_e5m2": 1, "fp16": 2,
                     "bf16": 2, "fp32": 4}
        out: List[Dict[str, Any]] = []
        for site, target in plan.items():
            b_new = fmt_bytes.get(target, _DTYPE_BYTES.get(target))
            if b_new is None:
                raise ValueError(f"what_if target {target!r} is not a "
                                 f"known format or HLO dtype")
            needle = site.lower()
            for r in self.rows:
                if needle not in r.scope.lower():
                    continue
                b_cur = _DTYPE_BYTES.get(r.dtype)
                if b_cur is None or b_new >= b_cur:
                    continue      # target not narrower — no what-if
                ratio = b_new / b_cur
                new_compute = r.compute_us * ratio
                new_memory = r.memory_us * ratio
                whatif = max(new_compute, new_memory)
                gain = max(0.0, (r.attainable_us - whatif)
                           * max(r.occurrences, 1))
                out.append({
                    "site": site, "op": r.name, "scope": r.scope,
                    "family": r.family, "fingerprint": r.fingerprint,
                    "dtype_from": r.dtype, "dtype_to": target,
                    "bound": r.bound,
                    "attainable_us": round(r.attainable_us, 3),
                    "whatif_attainable_us": round(whatif, 3),
                    "whatif_gain_us": round(gain, 3),
                    "measured_us": (None if r.measured_us is None
                                    else round(r.measured_us, 3)),
                    "occurrences": r.occurrences})
        out.sort(key=lambda e: -e["whatif_gain_us"])
        return out

    def worst_gaps(self, k: int = 5) -> List[Dict[str, Any]]:
        """The top-k ops by total time above their roofline — the
        committed, fingerprinted candidate list ROADMAP item 4's
        autotuner consumes (each entry a JSON-able dict; APX104's
        static tile-padding findings are the AOT complement)."""
        gaps = [r for r in self.rows
                if r.gap_us is not None and r.gap_us > 0]
        gaps.sort(key=lambda r: -r.gap_us)
        return [{"fingerprint": r.fingerprint, "op": r.name,
                 "family": r.family, "scope": r.scope,
                 "dtype": r.dtype, "shape": r.shape,
                 "bound": r.bound,
                 "measured_us": round(r.measured_us, 3),
                 "attainable_us": round(r.attainable_us, 3),
                 "gap_us": round(r.gap_us, 3),
                 "efficiency": round(r.efficiency, 4),
                 "occurrences": r.occurrences}
                for r in gaps[:k]]

    def table(self, top: int = 12) -> str:
        head = (f"roofline — device={self.device_kind or '?'} "
                f"peak={self.peak_flops / 1e12:.0f} TFLOP/s "
                f"hbm={self.hbm_bw / 1e9:.0f} GB/s "
                f"ops={len(self.rows)}")
        lines = [head,
                 f"{'op':<26} {'family':<11} {'bound':<8} "
                 f"{'meas_us':>8} {'attain':>8} {'eff':>6} {'gap_us':>8}"]
        rows = sorted(self.rows, key=lambda r: -(r.gap_us or 0.0))
        for r in rows[:top]:
            eff = f"{r.efficiency:.0%}" if r.efficiency is not None \
                else "n/a"
            lines.append(
                f"{r.name[:26]:<26} {r.family:<11} {r.bound:<8} "
                f"{_fmt_us(r.measured_us):>8} "
                f"{_fmt_us(r.attainable_us):>8} {eff:>6} "
                f"{_fmt_us(r.gap_us):>8}")
        fams = self.by_family()
        if fams:
            lines.append("by family: " + "  ".join(
                f"{k}={v['measured_us']:.0f}us"
                + (f"@{v['efficiency']:.0%}"
                   if v.get("efficiency") is not None else "")
                for k, v in list(fams.items())[:6]))
        return "\n".join(lines)

    def summary(self, k: int = 3) -> Dict[str, Any]:
        """JSON-able digest (the bench `roofline_worst_gap` column)."""
        ok, err = self.check_closure()
        gaps = self.worst_gaps(k)
        return {"n_ops": len(self.rows), "measured": self.measured,
                "device": self.device_kind,
                "closure_ok": bool(ok),
                "closure_err": round(err, 6),
                "worst_gaps": gaps,
                "worst_gap_us": gaps[0]["gap_us"] if gaps else None}

    def to_events(self, rank: int = 0, step: Optional[int] = None,
                  top: Optional[int] = None) -> List[Dict]:
        rows = self.rows if top is None else self.rows[:top]
        return [r.to_event(rank=rank, step=step) for r in rows]


def _classify_bound(flops: float, nbytes: float, compute_us: float,
                    memory_us: float) -> str:
    if compute_us <= 0 and memory_us <= 0:
        return "unknown"
    if flops > 0 and compute_us >= memory_us:
        return "compute"
    return "memory" if nbytes > 0 else "unknown"


def roofline_report(compiled=None, profile=None, *,
                    peak_flops: Optional[float] = None,
                    hbm_bw: Optional[float] = None,
                    device_kind: Optional[str] = None) -> RooflineReport:
    """Join analytic per-op cost with measured per-op device time
    against the chip's peak table.

    ``compiled`` — a compiled executable (``.lower(...).compile()``),
    or its optimized-HLO text, or None. ``profile`` — a
    :class:`~apex_tpu.prof.TraceProfile` (``prof.parse_trace``), or
    None for an AOT-only report (rows carry ``measured_us=None``).
    At least one of the two must be given. Measured ops absent from
    the compiled module (or when ``compiled`` is None) fall back to
    analytic costs parsed from their own xplane HLO metadata — which
    carries inline operand types — so the committed fixtures audit
    tf-free in CPU CI with no module at hand.

    ``peak_flops``/``hbm_bw`` default to the attached device's spec
    table (:data:`PEAK_FLOPS` / :data:`PEAK_HBM_BW`); on unknown chips
    (CPU) they are 0 and every row classifies ``unknown`` unless peaks
    are passed explicitly. AOT-only and never dispatches.
    """
    if compiled is None and profile is None:
        raise ValueError("roofline_report needs a compiled module, a "
                         "TraceProfile, or both")
    if device_kind is None:
        try:
            import jax
            device_kind = getattr(jax.devices()[0], "device_kind", "?")
        except Exception:
            device_kind = "?"
    if peak_flops is None:
        peak_flops = lookup_peak(PEAK_FLOPS, device_kind)
    if hbm_bw is None:
        hbm_bw = lookup_peak(PEAK_HBM_BW, device_kind)

    costs: Dict[str, Dict[str, Any]] = {}
    if compiled is not None:
        text = compiled if isinstance(compiled, str) else \
            compiled.as_text()
        costs = _module_costs(text)

    def _mk(name, cost, occurrences, measured_us, category=""):
        flops, nbytes = cost["flops"], cost["bytes"]
        cap = cost.get("mxu_cap", 1.0)
        compute_us = (flops / (peak_flops * cap) * 1e6
                      if peak_flops > 0 and flops > 0 else 0.0)
        memory_us = (nbytes / hbm_bw * 1e6
                     if hbm_bw > 0 and nbytes > 0 else 0.0)
        return RooflineRow(
            name=name, opcode=cost["opcode"],
            family=classify_family(cost["scope"], cost["opcode"],
                                   category),
            scope=cost["scope"], flops=flops, bytes=nbytes,
            occurrences=occurrences, measured_us=measured_us,
            compute_us=compute_us, memory_us=memory_us,
            bound=_classify_bound(flops, nbytes, compute_us, memory_us),
            dtype=_result_dtype(cost["shape"]), shape=cost["shape"],
            mxu_cap=cap, hlo=cost["hlo"])

    rows: List[RooflineRow] = []
    seen = set()
    profile_total = module_total = 0.0
    module_runs = 0
    if profile is not None:
        module_total = profile.module_total_us
        module_runs = profile.module_runs
        for rec in profile.ops:
            profile_total += rec.total_us
            cost = costs.get(rec.name)
            if cost is None:
                # analytic from the op's own metadata HLO (inline
                # operand types — the committed-fixture path)
                cost = _module_costs("ENTRY fallback {\n  "
                                     + rec.hlo + "\n}") .get(rec.name)
            if cost is None:
                cost = {"flops": 0.0, "bytes": 0.0, "opcode": rec.opcode,
                        "shape": "", "scope": "", "scope_raw": "",
                        "mxu_cap": 1.0, "hlo": rec.hlo[:400]}
                m = _OP_NAME_RE.search(rec.hlo)
                if m:
                    cost["scope"] = strip_scope(m.group(1))
            seen.add(rec.name)
            rows.append(_mk(rec.name, cost, rec.occurrences,
                            rec.avg_us, rec.category))
    for name, cost in costs.items():
        if name not in seen:
            rows.append(_mk(name, cost, 0, None))
    rows.sort(key=lambda r: (-(r.gap_us or 0.0),
                             -(r.measured_us or 0.0) * max(r.occurrences,
                                                           1),
                             -r.bytes))
    return RooflineReport(rows=rows, device_kind=device_kind,
                          peak_flops=peak_flops, hbm_bw=hbm_bw,
                          profile_total_us=profile_total,
                          module_total_us=module_total,
                          module_runs=module_runs)
