"""apexlint — static analysis for compiled training steps.

The reference apex kept mixed-precision training correct *by
construction* (cast lists, opt-level validation at initialize time);
apexlint closes the remaining gap by auditing what was actually traced
and compiled. Two passes, both strictly AOT (trace + compile only —
never a device dispatch; the ``lint/no-extra-dispatch`` compile-check
case pins that an observed step stays bit-identical):

- the **jaxpr pass** (:mod:`apex_tpu.lint.jaxpr_pass`) walks
  ``jax.make_jaxpr`` output: RNG-key reuse, f64 creep, fp32 matmuls
  inside an active half-precision amp policy, host callbacks / debug
  prints traced into the step;
- the **HLO pass** (:mod:`apex_tpu.lint.hlo_pass`) walks the optimized
  scheduled HLO (reusing :mod:`apex_tpu.prof.memory`'s buffer parser
  and the :mod:`apex_tpu.monitor` collective accounting): donation
  misses with wasted-HBM estimates, collectives outside any known
  named scope (implicit resharding) with wire-byte cost, host
  transfers, and off-tile-grid matmul padding waste.

Typical use — lint the step exactly as you run it (pass your jitted
function so its ``donate_argnums`` are what gets audited)::

    jstep = jax.jit(train_step, donate_argnums=(0, 1))
    report = lint.lint_step(jstep, state, batch_stats, x, y,
                            policy=policy)
    print(report.table())
    assert not report.errors

CLI: ``python scripts/apexlint.py --flagship both`` (the
``run_tier1.sh --smoke`` CI gate), or ``--hlo dump.txt`` for a
pre-dumped module. Findings stream to JSONL via
``MetricsLogger(lint_sink=...)`` and validate with
``scripts/check_metrics_schema.py --kind lint``. Rule catalog,
severities and the baseline-file workflow: docs/linting.md.
"""

from __future__ import annotations

from typing import Optional, Sequence

from apex_tpu.lint.findings import (Finding, Report, Rule, RULES,
                                    SEVERITIES, load_baseline,
                                    save_baseline)
from apex_tpu.lint.hlo_pass import lint_hlo_text
from apex_tpu.lint.jaxpr_pass import lint_jaxpr

__all__ = ["Finding", "Report", "Rule", "RULES", "SEVERITIES",
           "lint_step", "lint_jaxpr", "lint_hlo_text", "lint_hlo_file",
           "load_baseline", "save_baseline"]


def lint_step(fn, *args, policy=None, compiled=None, hlo_text=None,
              known_scopes: Sequence[str] = (),
              min_donation_bytes: int = 4096,
              rules: Optional[Sequence[str]] = None,
              fn_name: Optional[str] = None, **kwargs) -> Report:
    """Lint one training step with both passes. Strictly AOT.

    ``fn`` may be a plain callable or a jitted function — pass the
    jitted one so the HLO pass sees your real ``donate_argnums``
    (donation is part of what is being audited). The jaxpr pass traces
    ``fn`` with ``jax.make_jaxpr``; the HLO pass compiles it (or reuses
    ``compiled=`` / ``hlo_text=`` when the caller already has the
    executable, avoiding a second compile). ``policy`` activates the
    fp32-matmul-in-amp rule; ``known_scopes`` extends the
    implicit-resharding allowlist (regex fragments).
    """
    jaxpr_rules = {"rng-key-reuse", "f64-creep", "fp32-matmul-in-amp",
                   "host-callback-in-step"}
    findings = []
    if fn is not None and (rules is None
                           or jaxpr_rules & set(rules)):
        # skip the (potentially expensive) trace entirely when the
        # caller selected HLO-pass rules only — with compiled= that
        # makes lint_step compile-free AND trace-free
        findings += lint_jaxpr(fn, *args, policy=policy, **kwargs)
    hlo_rules = {"donation-miss", "implicit-resharding",
                 "host-transfer", "tile-padding"}
    if hlo_text is None and (rules is None or hlo_rules & set(rules)):
        # same economy as the trace skip above: no XLA compile when the
        # caller selected jaxpr-pass rules only
        if compiled is not None:
            hlo_text = compiled.as_text()
        elif fn is not None:
            from apex_tpu.prof import hlo as _hlo
            hlo_text = _hlo.compiled_hlo(fn, *args, **kwargs)
    if hlo_text:
        findings += lint_hlo_text(
            hlo_text, known_scopes=known_scopes,
            min_donation_bytes=min_donation_bytes, rules=rules)
    if rules is not None:
        findings = [f for f in findings if f.rule in set(rules)]
    if fn_name is None and fn is not None:
        fn_name = getattr(fn, "__name__", None) or type(fn).__name__
    return Report(findings, fn_name=fn_name)


def lint_hlo_file(path: str, *, known_scopes: Sequence[str] = (),
                  min_donation_bytes: int = 4096) -> Report:
    """HLO-pass-only lint of a dumped optimized-HLO text file
    (``scripts/dump_hlo.py`` output or an XLA dump)."""
    with open(path) as f:
        text = f.read()
    import os
    return Report(
        lint_hlo_text(text, known_scopes=known_scopes,
                      min_donation_bytes=min_donation_bytes),
        fn_name=os.path.basename(path))
