"""Weight normalization — `apex.reparameterization` rebuilt.

The reference implements fp16-safe weight norm with module hooks that
recompute ``w = g · v/‖v‖`` (in fp32) before every forward
(`apex/reparameterization/weight_norm.py:22-78`,
`reparameterization.py:4-151`). flax ships the same reparameterization as
``nn.WeightNorm``; this module re-exports it under the reference's API
shape and adds the ``remove`` operation (collapse (v, g) back into a
plain kernel — ``remove_weight_norm``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn


class WeightNorm(nn.Module):
    """``WeightNorm(layer)``: reparameterize ``layer``'s kernel(s) as
    direction × magnitude. fp16-safety: norms always accumulate in fp32
    (the entire point of the reference's implementation —
    `weight_norm.py:8-20` explains the fp16 underflow hazard)."""
    layer: nn.Module
    variable_filter: Any = None

    @nn.compact
    def __call__(self, *args, **kwargs):
        kw = {}
        if self.variable_filter is not None:
            kw["variable_filter"] = self.variable_filter
        wn = nn.WeightNorm(self.layer, use_scale=True, **kw)
        return wn(*args, **kwargs)


def apply_weight_norm(layer: nn.Module, name: Optional[str] = None,
                      dim: int = 0) -> nn.Module:
    """Constructor-style mirror of ``apex.reparameterization.
    apply_weight_norm(module)``. ``name``/``dim`` accepted for signature
    parity; flax normalizes per-feature along the last axis (computed in
    fp32, the fp16-safe norm the reference hooks exist for)."""
    del name, dim
    return WeightNorm(layer)


def _norm_but_last(v):
    red = tuple(range(v.ndim - 1))
    return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)),
                            axis=red, keepdims=True))


def remove_weight_norm(params):
    """Collapse a weight-normed ``params`` collection back to plain
    kernels: ``kernel = g · v/‖v‖`` materialized once — the reference's
    ``remove_weight_norm`` (`reparameterization.py:100-130`).

    flax layout in: ``{"Inner_0": {...kernel...},
    "WeightNorm_0": {"Inner_0/kernel/scale": g}}``; out: the same tree
    with scales folded in and the WeightNorm_* nodes dropped.
    """
    out = {k: v for k, v in params.items()
           if not str(k).startswith("WeightNorm")}
    out = jax.tree_util.tree_map(lambda x: x, out)  # shallow copy tree
    for k, sub in params.items():
        if not str(k).startswith("WeightNorm"):
            continue
        for skey, g in sub.items():
            parts = str(skey).split("/")          # path.../kernel/scale
            assert parts[-1] == "scale", skey
            node = out
            for p in parts[:-2]:
                node = node[p]
            kname = parts[-2]
            v = node[kname]
            node[kname] = (g * v.astype(jnp.float32) / _norm_but_last(v)
                           ).astype(v.dtype)
    return out
