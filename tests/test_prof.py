"""apex_tpu.prof tests — annotate / xplane parse / HLO cost analysis.

Mirrors the reference's pyprof tests (`tests/L0/run_pyprof_nvtx`,
`run_pyprof_data`): the nvtx tier asserts every wrapped call still
computes correctly and markers are emitted; the data tier feeds
hand-built kernel records through the analyzers. Here: named scopes must
appear in lowered HLO, the module interceptor must record call shapes,
the xplane parser is fed a hand-built XSpace proto, and cost analysis
must report real FLOPs for a matmul.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import prof


def test_scope_names_appear_in_hlo():
    def f(x):
        with prof.scope("my_marker_scope"):
            y = x @ x
        return jnp.tanh(y).sum()

    lowered = jax.jit(f).lower(jnp.ones((64, 64)))
    try:
        text = lowered.as_text(debug_info=True)
    except TypeError:
        # older jax: as_text has no debug_info kwarg and strips locs from
        # StableHLO — the scope still lands in compiled-HLO op metadata
        text = lowered.compile().as_text()
    assert "my_marker_scope" in text


def test_annotate_decorator_preserves_semantics():
    @prof.annotate("step")
    def f(x):
        return 2.0 * x

    np.testing.assert_allclose(f(jnp.arange(4.0)), [0, 2, 4, 6])


def test_annotate_modules_records_calls():
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(8)(x)
            return nn.Dense(4)(x)

    net = Net()
    x = jnp.ones((2, 16))
    params = net.init(jax.random.PRNGKey(0), x)
    with prof.annotate_modules() as records:
        out = net.apply(params, x)
    assert out.shape == (2, 4)
    paths = [r.path for r in records]
    assert any("Dense_0" in p for p in paths)
    assert any("Dense_1" in p for p in paths)
    dense0 = next(r for r in records if "Dense_0" in r.path)
    assert dense0.method == "__call__"
    assert ((2, 16), "float32") in jax.tree_util.tree_leaves(
        [dense0.args]) or str(dense0.args).count("16")


def test_cost_analysis_matmul_flops():
    def f(a, b):
        return a @ b

    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 64), jnp.float32)
    cost = prof.cost_analysis(f, a, b)
    # 2*M*N*K = 2*128*64*256 = 4.19e6; XLA may count slightly differently
    assert cost["flops"] >= 2 * 128 * 64 * 256 * 0.9
    assert cost["bytes_accessed"] > 0


def test_op_estimates_finds_dot():
    def f(a, b):
        return jnp.tanh(a @ b)

    a = jnp.ones((32, 64), jnp.float32)
    b = jnp.ones((64, 16), jnp.float32)
    ests = prof.op_estimates(f, a, b)
    assert ests, "no instructions parsed from optimized HLO"
    dots = [e for e in ests if e.opcode == "dot"]
    fusion_flops = sum(e.flops for e in ests)
    # the dot may stay top-level or be fused; either way some op should
    # carry the matmul flops when a top-level dot exists
    if dots:
        assert dots[0].flops == pytest.approx(2 * 32 * 16 * 64)
    assert all(e.bytes >= 0 for e in ests)
    assert fusion_flops >= 0


def _build_xspace(tmp_path):
    """Hand-build an XSpace proto shaped like a real TPU trace."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"

    md_mod = plane.event_metadata[1]
    md_mod.id = 1
    md_mod.name = "jit_step(123)"
    md_fus = plane.event_metadata[2]
    md_fus.id = 2
    md_fus.name = ("%fusion.3 = f32[128,128]{1,0:T(8,128)} "
                   "fusion(f32[128,128]{1,0} %p0), kind=kLoop, "
                   "calls=%fused_computation")
    md_conv = plane.event_metadata[3]
    md_conv.id = 3
    md_conv.name = ("%convolution.7 = f32[8,16,16,64]{3,2,1,0} "
                    "convolution(f32[8,16,16,32]{3,2,1,0} %x, "
                    "f32[3,3,32,64]{3,2,1,0} %w), dim_labels=b01f_01io->b01f")

    mods = plane.lines.add()
    mods.name = "XLA Modules"
    for i in range(2):
        ev = mods.events.add()
        ev.metadata_id = 1
        ev.offset_ps = i * 10**9
        ev.duration_ps = 500_000_000  # 500 us

    ops = plane.lines.add()
    ops.name = "XLA Ops"
    for i in range(2):
        ev = ops.events.add()
        ev.metadata_id = 2
        ev.duration_ps = 100_000_000  # 100 us
        ev = ops.events.add()
        ev.metadata_id = 3
        ev.duration_ps = 300_000_000  # 300 us

    p = tmp_path / "host.xplane.pb"
    p.write_bytes(xs.SerializeToString())
    return str(p)


def test_xplane_parser_synthetic(tmp_path):
    pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
    path = _build_xspace(tmp_path)
    tp = prof.parse_trace(path)
    assert tp.device == "/device:TPU:0"
    assert tp.module_runs == 2
    assert tp.module_total_us == pytest.approx(1000.0)
    assert len(tp.ops) == 2
    conv = tp.ops[0]  # sorted by total time desc: conv 600us > fusion 200us
    assert conv.opcode == "convolution"
    assert conv.category == "conv"
    assert conv.occurrences == 2
    assert conv.total_us == pytest.approx(600.0)
    fus = tp.ops[1]
    assert fus.category == "fusion.loop"
    assert fus.avg_us == pytest.approx(100.0)
    cats = tp.by_category()
    assert cats["conv"] == pytest.approx(600.0)
    assert "conv" in tp.table()


FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "resnet_step.xplane.pb")


def _block_tf(monkeypatch):
    import builtins
    real_import = builtins.__import__

    def block(name, *args, **kwargs):
        if name.startswith("tensorflow"):
            raise ModuleNotFoundError("No module named 'tensorflow'")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", block)


def test_xplane_parse_without_tensorflow(monkeypatch):
    """With the tf proto import blocked, the pure-python wire-format
    decoder parses the committed fixture — the tool justifying every
    perf claim no longer needs tensorflow (VERDICT r5 weak 6)."""
    _block_tf(monkeypatch)
    tp = prof.parse_trace(FIXTURE)
    assert tp.device == "/device:TPU:0"
    assert len(tp.ops) == 6


def test_xplane_corrupt_file_actionable_error(tmp_path, monkeypatch):
    """Undecodable bytes raise an actionable error naming the
    HLO-estimates fallback (the reference degrades its scaler import
    the same way, apex/amp/scaler.py:39-52)."""
    _block_tf(monkeypatch)
    path = tmp_path / "corrupt.xplane.pb"
    path.write_bytes(b"\x0a\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01")
    with pytest.raises(ValueError, match="op_estimates"):
        prof.parse_trace(str(path))


class TestXplaneFixture:
    """Pin the committed on-chip-shaped fixture's per-op table (pure
    decoder forced — no tensorflow on the decode path), in lockstep
    with scripts/make_xplane_fixture.py."""

    @pytest.fixture(autouse=True)
    def _pure(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_XPLANE_PURE", "1")

    def test_per_op_table(self):
        tp = prof.parse_trace(FIXTURE)
        assert tp.device == "/device:TPU:0"     # host plane skipped
        assert tp.module_runs == 2
        assert tp.module_total_us == pytest.approx(2000.0)
        rows = [(r.name, r.opcode, r.category, r.occurrences,
                 round(r.total_us, 1)) for r in tp.ops]
        assert rows == [
            ("fusion.31", "fusion", "fusion.output", 2, 184.5),
            ("convolution.7", "convolution", "conv", 2, 148.0),
            ("fusion.88", "fusion", "fusion.input", 2, 100.0),
            ("all-reduce.3", "all-reduce", "collective", 1, 41.0),
            ("custom-call.9", "custom-call", "custom-call", 1, 31.0),
            ("copy.5", "copy", "copy", 1, 12.5),
        ]
        assert tp.ops[0].avg_us == pytest.approx(92.25)

    def test_categories_and_scopes(self):
        tp = prof.parse_trace(FIXTURE)
        cats = tp.by_category()
        assert cats["conv"] == pytest.approx(148.0)
        assert cats["collective"] == pytest.approx(41.0)
        scopes = tp.by_scope(depth=2)
        # wrapper components (jit/jvp/transpose) are stripped; fwd and
        # bwd ops of the same user scope aggregate under one key
        assert scopes["amp/fwd"] == pytest.approx(463.5)
        assert scopes["ddp/sync_gradients"] == pytest.approx(41.0)
        assert scopes["(unscoped)"] == pytest.approx(12.5)
        assert "conv" in tp.table()

    def test_parity_with_tensorflow_decoder(self, monkeypatch):
        """When tensorflow IS available its decoder must agree with the
        pure one bit for bit (skip silently where it isn't)."""
        pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
        tp_pure = prof.parse_trace(FIXTURE)
        monkeypatch.delenv("APEX_TPU_XPLANE_PURE")
        tp_tf = prof.parse_trace(FIXTURE)
        key = lambda tp: [(r.name, r.opcode, r.occurrences, r.total_us,
                           r.hlo) for r in tp.ops]
        assert key(tp_pure) == key(tp_tf)
        assert (tp_pure.device, tp_pure.module_runs,
                tp_pure.module_total_us) == \
            (tp_tf.device, tp_tf.module_runs, tp_tf.module_total_us)


def test_trace_capture_roundtrip(tmp_path):
    """End-to-end: capture a real trace, parse it without raising."""
    logdir = str(tmp_path / "trace")

    @jax.jit
    def f(x):
        return jnp.tanh(x @ x).sum()

    x = jnp.ones((64, 64))
    f(x).block_until_ready()
    with prof.trace(logdir):
        np.asarray(f(x))
    found = prof.parse_trace.__globals__["latest_xplane"](logdir)
    assert found is not None, "trace produced no xplane.pb"
    tp = prof.parse_trace(logdir)
    # CPU backend has no device plane; parser must degrade, not raise
    assert isinstance(tp.ops, list)


def test_profile_step_cpu():
    def f(x):
        return (x @ x).sum()

    rep = prof.profile_step(f, jnp.ones((64, 64)), iters=2, warmup=1)
    assert rep.cost["flops"] > 0
    assert rep.wall_us > 0
    assert isinstance(rep.table(), str)
    # CPU: no device plane → mfu computes to 0 (peak unknown)
    assert rep.mfu() == 0.0


def test_profile_step_cleans_its_tempdir():
    """Default profile_step must not leak mkdtemp trace dirs (ISSUE-1
    satellite): auto-created logdirs are removed after parsing,
    keep_trace=True keeps them, explicit logdirs are never touched."""
    import shutil

    def f(x):
        return (x * 2.0).sum()

    x = jnp.ones((16,))
    rep = prof.profile_step(f, x, iters=1, warmup=1)
    assert rep.logdir == ""            # removed; nothing to point at

    rep = prof.profile_step(f, x, iters=1, warmup=1, keep_trace=True)
    assert rep.logdir and os.path.isdir(rep.logdir)
    shutil.rmtree(rep.logdir, ignore_errors=True)

    import tempfile
    explicit = tempfile.mkdtemp(prefix="apex_tpu_prof_explicit_")
    try:
        rep = prof.profile_step(f, x, iters=1, warmup=1, logdir=explicit)
        assert rep.logdir == explicit
        assert os.path.isdir(explicit)  # caller-owned: never removed
    finally:
        shutil.rmtree(explicit, ignore_errors=True)


def test_mfu_prints_na_on_unknown_device():
    """On CPU (unknown peak) table() must say mfu=n/a, never 0.0%."""
    def f(x):
        return (x @ x).sum()

    rep = prof.profile_step(f, jnp.ones((32, 32)), iters=1, warmup=1)
    if prof.device_peak_flops():
        assert "mfu=n/a" not in rep.table()
    else:
        assert "mfu=n/a" in rep.table()
        assert "mfu=0.0%" not in rep.table()


def test_opcode_categories_modern_traces():
    """Parser regression over synthetic HLO instruction strings for the
    opcodes modern traces emit (ISSUE-1 satellite): ragged-all-to-all,
    dynamic-(update-)slice, while."""
    from apex_tpu.prof.xplane import _categorize, _OPCODE_RE

    cases = [
        ("%ragged-all-to-all.3 = bf16[1024,128]{1,0:T(8,128)(2,1)} "
         "ragged-all-to-all(bf16[1024,128]{1,0} %p0, s32[8]{0} %sizes), "
         "replica_groups={{0,1,2,3,4,5,6,7}}",
         "ragged-all-to-all", "collective"),
        ("%dynamic-slice.5 = f32[1,128]{1,0} dynamic-slice(f32[8,128]{1,0} "
         "%buf, s32[] %i, s32[] %zero), dynamic_slice_sizes={1,128}",
         "dynamic-slice", "slice"),
        ("%dynamic-update-slice.9 = f32[8,128]{1,0} dynamic-update-slice("
         "f32[8,128]{1,0} %buf, f32[1,128]{1,0} %upd, s32[] %i, s32[] %z)",
         "dynamic-update-slice", "slice"),
        ("%while.31 = (s32[]{:T(128)}, f32[8,128]{1,0}) while((s32[], "
         "f32[8,128]) %init), condition=%cond.2, body=%body.3",
         "while", "control-flow"),
        ("%all-to-all.1 = f32[64]{0} all-to-all(f32[64]{0} %p0), "
         "dimensions={0}", "all-to-all", "collective"),
        ("%all-reduce.7 = f32[64]{0} all-reduce(f32[64]{0} %p0), "
         "to_apply=%add", "all-reduce", "collective"),
    ]
    for text, want_opcode, want_cat in cases:
        m = _OPCODE_RE.match(text)
        assert m, f"opcode regex missed: {text[:60]}"
        assert m.group("opcode") == want_opcode
        assert _categorize(m.group("opcode"), text) == want_cat


def test_by_scope_aggregates_named_scopes():
    """TraceProfile.by_scope over synthetic op records: transform
    wrappers (jit/transpose(jvp)/vmap) are stripped so the same
    trace.span name aggregates under one key at the requested depth;
    metadata-less ops land under (unscoped)."""
    from apex_tpu.prof.xplane import OpRecord, TraceProfile

    def rec(name, us, op_name=None):
        hlo = f"%{name} = f32[8]{{0}} fusion(f32[8]{{0}} %p0)"
        if op_name is not None:
            hlo += f', metadata={{op_name="{op_name}"}}'
        return OpRecord(name=name, opcode="fusion", category="fusion",
                        occurrences=1, total_us=us, hlo=hlo)

    tp = TraceProfile(path="", device="d", module_runs=1,
                      module_total_us=0.0, ops=[
        rec("f.1", 10.0, "jit(step)/amp/fwd/conv"),
        rec("f.2", 5.0, "jit(step)/transpose(jvp(step))/amp/fwd/dot"),
        rec("f.3", 2.0, "jit(step)/vmap(step)/amp/unscale/mul"),
        rec("f.4", 1.0, "jit(step)"),          # wrappers only
        rec("f.5", 4.0),                       # no metadata at all
    ])
    got = tp.by_scope(depth=2)
    assert got["amp/fwd"] == 15.0              # fwd + its transpose
    assert got["amp/unscale"] == 2.0
    assert got["(unscoped)"] == 5.0            # f.4 + f.5
    # depth=1 folds everything under the top-level scope
    assert tp.by_scope(depth=1)["amp"] == 17.0


_REPO_ROOT = str(__import__("pathlib").Path(__file__).resolve().parents[1])


def test_cli_on_synthetic_trace(tmp_path):
    """`python -m apex_tpu.prof <logdir>` — the pyprof.parse/prof CLI
    equivalent — renders the op table from a trace dir."""
    pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
    import subprocess, sys
    path = _build_xspace(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.prof", str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO_ROOT)
    assert r.returncode == 0, r.stderr
    assert "convolution" in r.stdout
    r2 = subprocess.run(
        [sys.executable, "-m", "apex_tpu.prof", str(tmp_path), "--csv"],
        capture_output=True, text=True, cwd=_REPO_ROOT)
    assert r2.returncode == 0
    assert r2.stdout.startswith("name,category,occurrences,total_us")


def test_cli_empty_dir(tmp_path):
    import subprocess, sys
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.prof", str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO_ROOT)
    assert r.returncode == 1
