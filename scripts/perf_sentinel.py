#!/usr/bin/env python
"""perf_sentinel — the noise-aware perf-regression gate over bench JSON
trajectories (apex_tpu.prof.sentinel as a CLI; pure stdlib — CI and
log-shipping hosts run it without jax).

    python scripts/perf_sentinel.py --check BENCH_r01.json ... BENCH_r05.json
    python scripts/perf_sentinel.py --check BENCH_r0*.json --replay
    python scripts/perf_sentinel.py --check ... --write-baseline "reason"

Judges the NEWEST metric-bearing row against robust median/MAD
baselines built from the earlier rows, direction-aware (only the
degradation direction fires; see apex_tpu/prof/sentinel.py for the
metric table and thresholds). ``--replay`` backtests every row against
its prefix. Rows without metrics (failed bench runs commit
``"parsed": null``) are skipped with a note.

Waivers: ``--baseline scripts/perf_baseline.json`` (committed; starts
empty) suppresses fingerprinted, explicitly-accepted regressions;
``--write-baseline REASON`` records the current regressions there with
``allow_to`` floors so further degradation re-fires. ``--jsonl`` streams
one ``kind="regress"`` event per verdict
(``check_metrics_schema.py --kind roofline`` validates).

Exit status: 0 clean (or waived), 1 unwaived regression, 2 usage/IO.
Run by ``run_tier1.sh --smoke`` over the committed r01–r05 trajectory;
``scripts/roofline_audit.py --cpu8`` asserts the seeded-regression
positive and the no-change negative twin.
"""

import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_sentinel():
    """Load apex_tpu.prof.sentinel WITHOUT importing the package (the
    package __init__ pulls jax; the sentinel itself is pure stdlib, so
    CI/log hosts can run this gate without an ML stack)."""
    path = os.path.join(_REPO, "apex_tpu", "prof", "sentinel.py")
    spec = importlib.util.spec_from_file_location(
        "apex_tpu_prof_sentinel", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod       # dataclasses resolve through here
    spec.loader.exec_module(mod)
    return mod


sentinel = _load_sentinel()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    files, baseline_path, jsonl, json_out = [], None, None, None
    replay = False
    write_reason = None
    it = iter(argv)
    for a in it:
        if a in ("-h", "--help"):
            print(__doc__)
            return 2
        elif a == "--check":
            pass                        # files follow positionally
        elif a == "--baseline":
            baseline_path = next(it, None)
        elif a == "--write-baseline":
            write_reason = next(it, "accepted regression")
        elif a == "--jsonl":
            jsonl = next(it, None)
        elif a == "--json":
            json_out = next(it, None)
        elif a == "--replay":
            replay = True
        elif a.startswith("-"):
            print(f"unknown flag {a!r}\n{__doc__}", file=sys.stderr)
            return 2
        else:
            files.append(a)
    if not files:
        print(__doc__)
        return 2

    if write_reason is not None and not baseline_path:
        print("--write-baseline needs --baseline PATH (the committed "
              "waiver file, e.g. scripts/perf_baseline.json)",
              file=sys.stderr)
        return 2

    try:
        waivers = sentinel.load_baseline(baseline_path) if baseline_path \
            else {}
        # the baseline may also DECLARE extra judged metrics (the
        # "metrics" section — e.g. ddp_wire_bytes over the hierarchical
        # sync row), direction-aware and waiverable like the built-ins
        extra = (sentinel.metric_specs_from_baseline(baseline_path)
                 if baseline_path else [])
    except ValueError as e:
        # a corrupt committed waiver file is a config error (exit 2),
        # not an "unwaived regression" (exit 1)
        print(f"perf_sentinel: {baseline_path}: {e}", file=sys.stderr)
        return 2
    specs = tuple(sentinel.METRICS) + tuple(extra)
    rows = sentinel.load_rows(files, specs=specs)

    # a gate that judged NOTHING must not report clean: unreadable
    # inputs (a moved trajectory, an unexpanded glob passed literally)
    # or a trajectory with zero metric-bearing rows is an IO/usage
    # error, not a pass. Failed-bench rows ("parsed": null) are still
    # tolerated — they are readable and skipped with a note.
    unreadable = [r for r in rows if r["note"]
                  and r["note"].startswith("unreadable")]
    if unreadable:
        for r in unreadable:
            print(f"perf_sentinel: {r['path']}: {r['note']}",
                  file=sys.stderr)
        return 2
    if not any(r["metrics"] for r in rows):
        print("perf_sentinel: no metric-bearing rows in "
              f"{len(rows)} input file(s) — nothing judged",
              file=sys.stderr)
        return 2

    if replay:
        reports = sentinel.replay_trajectory(rows, waivers=waivers,
                                             specs=specs)
        bad = [r for r in reports if not r.ok]
        for rep in reports:
            tag = "ok" if rep.ok else "REGRESSED"
            print(f"-- {rep.subject}: {tag}")
            if not rep.ok:
                print(rep.table())
        if not reports:
            reports = [sentinel.SentinelReport(
                verdicts=[], subject=None, notes=["nothing judgeable"])]
        report = reports[-1]
        # the emitted streams carry EVERY prefix-report's verdicts — a
        # mid-trajectory regression must appear in the JSONL that the
        # exit code judges, not only in the final row's verdicts
        events = [ev for rep in reports for ev in rep.to_events()]
    else:
        report = sentinel.check_trajectory(rows, waivers=waivers,
                                           specs=specs)
        bad = [] if report.ok else [report]
        print(f"-- judging {report.subject} against "
              f"{sum(1 for r in rows if r['metrics']) - 1} prior rows")
        print(report.table())
        events = report.to_events()

    if jsonl:
        with open(jsonl, "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
    if json_out:
        with open(json_out, "w") as f:
            json.dump({"ok": not bad,
                       "n_regressions": sum(len(r.regressions)
                                            for r in bad),
                       "verdicts": events}, f, indent=1)
    if write_reason is not None and baseline_path:
        sentinel.save_baseline(baseline_path, report,
                               reason=write_reason)
        print(f"wrote waivers to {baseline_path}")
        return 0

    if bad:
        n = sum(len(r.regressions) for r in bad)
        print(f"perf_sentinel: {n} unwaived regression(s)",
              file=sys.stderr)
        return 1
    print("perf_sentinel: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
