"""Legacy loss scalers — `apex/fp16_utils/loss_scaler.py:10-186` rebuilt.

Thin classful mirrors over the functional scaler state in
:mod:`apex_tpu.amp.scaler`, keeping the legacy defaults (dynamic init
2**32, window 1000) that differ from the amp scaler's (2**16, 2000).
These exist for API parity; new code should thread
``amp.LossScaleState`` through the step directly.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.amp.scaler import (LossScaleConfig, LossScaleState,
                                 loss_scale_init, loss_scale_update,
                                 unscale_grads)
from apex_tpu.utils import tree_all_finite


class LossScaler:
    """Static scaler (`loss_scaler.py:10-60`)."""

    def __init__(self, scale: float = 1.0):
        self.cfg = LossScaleConfig(init_scale=scale, dynamic=False)
        self.state = loss_scale_init(self.cfg)

    @property
    def loss_scale(self) -> float:
        return float(self.state.loss_scale)

    def scale_gradient(self, grads):
        return unscale_grads(grads, self.state)[0]

    def update_scale(self, overflow: bool) -> None:
        pass  # static

    def has_overflow(self, grads) -> bool:
        return not bool(tree_all_finite(grads))

    def backward(self, loss):
        return jnp.asarray(loss, jnp.float32) * self.state.loss_scale


class DynamicLossScaler(LossScaler):
    """Dynamic scaler with legacy schedule (`loss_scaler.py:63-186`):
    init 2**32, halve on overflow, double after 1000 clean steps."""

    def __init__(self, init_scale: float = 2.0 ** 32, scale_factor: float = 2.0,
                 scale_window: int = 1000):
        self.cfg = LossScaleConfig(
            init_scale=init_scale, growth_factor=scale_factor,
            backoff_factor=1.0 / scale_factor, growth_interval=scale_window,
            max_loss_scale=init_scale, dynamic=True)
        self.state = loss_scale_init(self.cfg)

    def update_scale(self, overflow: bool) -> None:
        self.state = loss_scale_update(
            self.state, jnp.logical_not(jnp.bool_(overflow)), self.cfg)
