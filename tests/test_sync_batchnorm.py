"""SyncBatchNorm vs a NumPy reference on the *combined* batch.

Mirrors `tests/distributed/synced_batchnorm/two_gpu_unit_test.py` (fwd/bwd
against combined-batch stats), `two_gpu_test_different_batch_size.py`
(count-weighted Welford for unequal batches, via valid_count),
`test_groups.py` (partitioned stats groups), and the fused relu/add variant.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel


def np_batchnorm(x, scale, bias, eps=1e-5):
    """Reference BN over the full combined batch (channel-last)."""
    axes = tuple(range(x.ndim - 1))
    mean = x.mean(axis=axes)
    var = x.var(axis=axes)
    y = (x - mean) / np.sqrt(var + eps)
    return y * scale + bias, mean, var


def _run_sharded(mesh, fn, *args, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(*args)


class TestSyncBNForward:
    def test_matches_combined_batch(self, mesh8):
        rng = np.random.RandomState(0)
        x = rng.randn(16, 4, 4, 8).astype(np.float32)  # NHWC, N split 8 ways
        scale = rng.rand(8).astype(np.float32) + 0.5
        bias = rng.randn(8).astype(np.float32)

        def fwd(xs):
            y, mean, var, count = parallel.sync_batch_norm(
                xs, jnp.asarray(scale), jnp.asarray(bias),
                axis_name="data")
            return y, mean, var

        y, mean, var = _run_sharded(
            mesh8, fwd, jnp.asarray(x),
            in_specs=P("data"), out_specs=(P("data"), P(), P()))

        y_ref, mean_ref, var_ref = np_batchnorm(x, scale, bias)
        np.testing.assert_allclose(np.asarray(mean), mean_ref, atol=1e-5)
        np.testing.assert_allclose(np.asarray(var), var_ref, atol=1e-5)
        np.testing.assert_allclose(np.asarray(y), y_ref, atol=1e-4)

    def test_unequal_batch_sizes(self, mesh8):
        """Ragged local batches via zero-padding + valid_count: combine must
        be count-weighted (`two_gpu_test_different_batch_size.py`)."""
        rng = np.random.RandomState(1)
        C = 4
        # device i contributes i+1 valid rows (rest zero padding)
        counts = np.arange(1, 9)
        rows = []
        for i, n in enumerate(counts):
            block = np.zeros((8, C), np.float32)
            block[:n] = rng.randn(n, C)
            rows.append(block)
        x = np.stack(rows)  # (8, 8, C)
        valid = np.concatenate([np.full(n, True).tolist()
                                + np.full(8 - n, False).tolist()
                                for n in counts])
        flat_valid = np.concatenate([r[:n] for r, n in zip(rows, counts)])

        def fwd(xs, n_valid):
            # zero-padded local batch + valid_count: the public API path
            return parallel.sync_moments(
                xs, axis_name="data", reduce_axes=(0,),
                valid_count=n_valid[0])

        mean, var, count = _run_sharded(
            mesh8, fwd,
            jnp.asarray(x).reshape(64, C), jnp.asarray(counts, jnp.float32),
            in_specs=(P("data"), P("data")), out_specs=(P(), P(), P()))

        np.testing.assert_allclose(float(count), counts.sum())
        np.testing.assert_allclose(np.asarray(mean),
                                   flat_valid.mean(axis=0), atol=1e-5)
        np.testing.assert_allclose(np.asarray(var),
                                   flat_valid.var(axis=0), atol=1e-5)

    def test_stats_groups(self, mesh8):
        """Two stats groups of 4: each group normalizes with its own
        combined stats (`test_groups.py`)."""
        rng = np.random.RandomState(2)
        x = rng.randn(16, 4).astype(np.float32)
        groups = parallel.syncbn_stats_groups(8, 4)
        assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]

        def fwd(xs):
            mean, var, count = parallel.sync_moments(
                xs, axis_name="data", reduce_axes=(0,),
                axis_index_groups=groups)
            return jax.lax.all_gather(mean, "data")

        means = _run_sharded(mesh8, fwd, jnp.asarray(x),
                             in_specs=P("data"), out_specs=P())
        # first 4 devices see rows 0..7, last 4 see rows 8..15
        np.testing.assert_allclose(np.asarray(means)[0],
                                   x[:8].mean(axis=0), atol=1e-5)
        np.testing.assert_allclose(np.asarray(means)[7],
                                   x[8:].mean(axis=0), atol=1e-5)

    def test_fused_add_relu(self, mesh8):
        rng = np.random.RandomState(3)
        x = rng.randn(16, 4).astype(np.float32)
        z = rng.randn(16, 4).astype(np.float32)

        def fwd(xs, zs):
            y, *_ = parallel.sync_batch_norm(
                xs, None, None, axis_name="data", z=zs, relu=True)
            return y

        y = _run_sharded(mesh8, fwd, jnp.asarray(x), jnp.asarray(z),
                         in_specs=(P("data"), P("data")),
                         out_specs=P("data"))
        mean, var = x.mean(0), x.var(0)
        expect = np.maximum((x - mean) / np.sqrt(var + 1e-5) + z, 0.0)
        np.testing.assert_allclose(np.asarray(y), expect, atol=1e-4)


class TestSyncBNBackward:
    def test_grads_match_full_batch_bn(self, mesh8):
        """d(loss)/dx through SyncBN across shards == through plain BN on
        the combined batch — the hand-written backward of the reference
        (`optimized_sync_batchnorm_kernel.py:77-119`) via autodiff."""
        rng = np.random.RandomState(4)
        x = rng.randn(16, 4).astype(np.float32)
        scale = rng.rand(4).astype(np.float32) + 0.5
        bias = rng.randn(4).astype(np.float32)

        def loss_sharded(xs):
            y, *_ = parallel.sync_batch_norm(
                xs, jnp.asarray(scale), jnp.asarray(bias),
                axis_name="data")
            return jax.lax.psum(jnp.sum(y * y), "data")

        def sharded_grad(xs):
            return jax.grad(loss_sharded)(xs)

        gx = _run_sharded(mesh8, sharded_grad, jnp.asarray(x),
                          in_specs=P("data"), out_specs=P("data"))

        def loss_full(xf):
            axes = (0,)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.mean(jnp.square(xf), axis=axes) - mean**2
            y = (xf - mean) / jnp.sqrt(var + 1e-5) * scale + bias
            return jnp.sum(y * y)

        gx_ref = jax.grad(loss_full)(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   atol=1e-3)

    def test_scale_bias_grads(self, mesh8):
        rng = np.random.RandomState(5)
        x = rng.randn(16, 4).astype(np.float32)
        sb = {"scale": jnp.ones(4), "bias": jnp.zeros(4)}

        def loss(sb_, xs):
            y, *_ = parallel.sync_batch_norm(
                xs, sb_["scale"], sb_["bias"], axis_name="data")
            return jax.lax.psum(jnp.sum(y**3), "data")

        def g(sb_, xs):
            # loss is psum'd (replicated), and cross-device terms flow back
            # through the stat collectives' transposes, so every device
            # already holds the full gradient; pmean collapses rounding.
            return jax.tree_util.tree_map(
                lambda t: jax.lax.pmean(t, "data"),
                jax.grad(loss)(sb_, xs))

        got = _run_sharded(mesh8, lambda xs: g(sb, xs), jnp.asarray(x),
                           in_specs=P("data"), out_specs=P())

        def loss_full(sb_):
            mean, var = x.mean(0), x.var(0)
            y = (jnp.asarray(x) - mean) / np.sqrt(var + 1e-5)
            y = y * sb_["scale"] + sb_["bias"]
            return jnp.sum(y**3)

        ref = jax.grad(loss_full)(sb)
        np.testing.assert_allclose(np.asarray(got["scale"]),
                                   np.asarray(ref["scale"]), rtol=1e-3)
        np.testing.assert_allclose(np.asarray(got["bias"]),
                                   np.asarray(ref["bias"]), rtol=1e-3)


class TestSyncBNModule:
    def test_module_train_and_eval(self, mesh8):
        rng = np.random.RandomState(6)
        x = rng.randn(16, 4, 4, 3).astype(np.float32)
        bn = parallel.SyncBatchNorm(num_features=3, axis_name="data",
                                    momentum=0.5)
        variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))

        def train_fwd(xs):
            y, mutated = bn.apply(variables, xs,
                                  mutable=["batch_stats"])
            return y, mutated["batch_stats"]

        y, stats = _run_sharded(mesh8, train_fwd, jnp.asarray(x),
                                in_specs=P("data"),
                                out_specs=(P("data"), P()))
        mean_ref = x.mean(axis=(0, 1, 2))
        var_ref = x.var(axis=(0, 1, 2))
        n = x.size // 3
        np.testing.assert_allclose(np.asarray(stats["mean"]),
                                   0.5 * mean_ref, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(stats["var"]),
            0.5 * 1.0 + 0.5 * var_ref * n / (n - 1), atol=1e-4)

        # eval uses running stats, no collectives needed
        y_eval = bn.apply(
            {"params": variables.get("params", {}),
             "batch_stats": stats},
            jnp.asarray(x), use_running_average=True)
        assert y_eval.shape == x.shape

    def test_convert_interceptor(self, mesh8):
        """Unmodified flax BatchNorm syncs stats inside the context."""
        import flax.linen as nn
        rng = np.random.RandomState(7)
        x = rng.randn(16, 4).astype(np.float32)
        bn = nn.BatchNorm(use_running_average=False, momentum=0.9)
        variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))

        def fwd(xs):
            with parallel.convert_sync_batchnorm("data"):
                y, _ = bn.apply(variables, xs, mutable=["batch_stats"])
            return y

        y = _run_sharded(mesh8, fwd, jnp.asarray(x),
                         in_specs=P("data"), out_specs=P("data"))
        y_ref, _, _ = np_batchnorm(x, np.ones(4, np.float32),
                                   np.zeros(4, np.float32))
        np.testing.assert_allclose(np.asarray(y), y_ref, atol=1e-4)
