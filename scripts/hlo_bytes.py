"""Byte-ledger attribution of an optimized-HLO dump.

Parses the entry computation of a dumped module (scripts/dump_hlo.py),
estimates per-instruction HBM traffic from operand/output shapes, and
groups it by block/layer (from op_name metadata) and by op class. This
is the accounting tool behind PERF.md's "where do the bytes go" tables —
the reference reads nvprof SQLite for the same question
(`apex/pyprof/prof/`); XLA's serialized HLO carries the shapes already.

Usage: python scripts/hlo_bytes.py HLO.txt [--by block|class] [--top N]

Caveats: traffic is estimated as sum(unique operand bytes) + output
bytes per entry instruction — intra-fusion temporaries are free,
parameters/constants counted once per use, and S(1)/S(2) (scoped/SMEM)
annotations are ignored; numbers track XLA's cost analysis within a few
percent on the bench step.
"""

import re
import sys
from collections import defaultdict

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e5m2": 1, "f8e4m3fn": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "token": 0,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def shape_bytes(text):
    """Total bytes of every shape literal in `text` (handles tuples)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
# the opcode is the first lowercase word followed by "(": layout
# annotations only contain T(...), S(...) and (2,1) groups, none of
# which a [a-z][\w-]*\( pattern matches
_OPCODE_RE = re.compile(r" ([a-z][a-z0-9_-]*)\(")


def parse_entry(path):
    """Yield (name, opcode, out_bytes, args, op_name) per entry op."""
    with open(path) as f:
        text = f.read()
    entry = text[text.rindex("ENTRY "):]
    for line in entry.splitlines():
        if " = " not in line:
            continue
        lhs, rhs = line.split(" = ", 1)
        name = lhs.strip().lstrip("%")
        m = _OPCODE_RE.search(rhs)
        if not m:
            continue
        opcode = m.group(1)
        out_b = shape_bytes(rhs[:m.start()])
        args = rhs[m.end():]
        args = args.split("metadata=")[0].split("backend_config=")[0]
        args = args.split("calls=")[0].split("kind=")[0]
        mo = _OPNAME_RE.search(line)
        yield name, opcode, out_b, args, (mo.group(1) if mo else "")


def main():
    path = sys.argv[1]
    by = "block"
    top = 40
    if "--by" in sys.argv:
        by = sys.argv[sys.argv.index("--by") + 1]
    if "--top" in sys.argv:
        top = int(sys.argv[sys.argv.index("--top") + 1])

    # first pass: output bytes per instruction name (definition map)
    defs = {}
    rows = []
    for name, opcode, out_b, args, op_name in parse_entry(path):
        defs[name] = out_b
        rows.append((name, opcode, out_b, args, op_name))

    groups = defaultdict(float)
    cls_groups = defaultdict(float)
    total = 0.0
    for name, opcode, out_b, args, op_name in rows:
        if opcode in ("parameter", "constant", "get-tuple-element",
                      "tuple", "bitcast"):
            continue
        in_b = 0
        seen = set()
        for ref in re.findall(r"%([\w.-]+)", args):
            if ref in defs and ref not in seen:
                seen.add(ref)
                in_b += defs[ref]
        traffic = out_b + in_b
        total += traffic
        # group key: the model block from op_name, else the opcode
        key = opcode
        m = re.search(r"(BottleneckBlock_\d+|stem\w*|Dense_\d+|_BN_\d+"
                      r"|FusedSGD|ConvBNAct_\d+)", op_name)
        blk = m.group(1) if m else (op_name.split("/")[1]
                                    if op_name.count("/") > 1 else opcode)
        fwd = "jvp" in op_name and "transpose" not in op_name
        groups[f"{blk}{'  [fwd]' if fwd else ' [bwd]' if 'transpose' in op_name else ''}"] += traffic
        cls_groups[opcode] += traffic

    sel = groups if by == "block" else cls_groups
    print(f"total est. traffic: {total/1e9:.1f} GB "
          f"({len(rows)} entry instructions)")
    for k, v in sorted(sel.items(), key=lambda kv: -kv[1])[:top]:
        print(f"  {v/1e9:8.2f} GB  {k}")


if __name__ == "__main__":
    main()
