"""Fused optimizer update kernels over the flat arena.

TPU-native rebuild of the reference's optimizer functors
(`csrc/multi_tensor_adam.cu:24-120`, `multi_tensor_sgd_kernel.cu:30-180`,
`multi_tensor_adagrad.cu`, `multi_tensor_lamb.cu:41-320`,
`multi_tensor_novograd.cu`): one Pallas kernel pass updates every parameter
of a dtype partition — parameters, gradients and optimizer state are flat
1-D buffers (apex_tpu.arena), walked in (512, 128) VMEM blocks.

Algorithm flags (adam_w, nesterov, ...) are *static* — each combination
compiles a specialized kernel, like the reference's template instantiations.
Runtime scalars (lr, betas, step count, grad scale) ride in SMEM so learning
rate schedules don't trigger recompilation.

All kernels compute in fp32 regardless of storage dtype and can emit an
additional low-precision parameter copy in the same pass (the reference's
depth-4 SGD / `reversible_adam` p_copy outputs, used to keep fp16 model
params in sync with fp32 masters at zero extra bandwidth).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import launch


def _launch(kernel, inputs, out_dtypes, scalars):
    """Elementwise arena kernel via the shared launcher: all outputs are
    full block buffers."""
    return launch(kernel, inputs, outs=[("block", dt) for dt in out_dtypes],
                  scalars=scalars)


# --- Adam / AdamW (`multi_tensor_adam.cu:24-120`) ---------------------------

def _adam_kernel(adam_w, has_copy, scalars, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref, *copy_ref):
    lr, b1, b2, eps, wd, bc1, bc2, gscale = (scalars[i] for i in range(8))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * gscale
    m = m_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    if not adam_w:           # L2-regularization mode: wd folded into grad
        g = g + wd * p
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    m_hat = m / bc1
    v_hat = v / bc2
    update = m_hat / (jnp.sqrt(v_hat) + eps)
    if adam_w:               # decoupled weight decay
        update = update + wd * p
    p = p - lr * update

    po_ref[:] = p.astype(po_ref.dtype)
    mo_ref[:] = m.astype(mo_ref.dtype)
    vo_ref[:] = v.astype(vo_ref.dtype)
    if has_copy:
        copy_ref[0][:] = p.astype(copy_ref[0].dtype)


def adam_update(p, g, m, v, *, lr, beta1, beta2, eps, weight_decay, step,
                adam_w_mode=True, bias_correction=True, grad_scale=1.0,
                param_copy_dtype=None):
    """One fused Adam/AdamW step over a flat partition.

    ``step`` is the 1-based step count *after* increment (traced ok).
    Returns (p, m, v) or (p, m, v, p_copy) when ``param_copy_dtype`` is set.
    """
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bc1 = 1.0 - jnp.power(jnp.float32(beta1), step)
        bc2 = 1.0 - jnp.power(jnp.float32(beta2), step)
    else:
        bc1 = bc2 = jnp.float32(1.0)
    scalars = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                         (lr, beta1, beta2, eps, weight_decay, bc1, bc2,
                          grad_scale)])
    out_dtypes = [p.dtype, m.dtype, v.dtype]
    if param_copy_dtype is not None:
        out_dtypes.append(jnp.dtype(param_copy_dtype))
    kernel = functools.partial(_adam_kernel, adam_w_mode,
                               param_copy_dtype is not None)
    return _launch(kernel, [p, g, m, v], out_dtypes, scalars)


# --- SGD (`multi_tensor_sgd_kernel.cu:30-180`) ------------------------------

def _sgd_kernel(nesterov, wd_after_momentum, has_copy,
                scalars, p_ref, g_ref, m_ref, po_ref, mo_ref, *copy_ref):
    lr, momentum, dampening, wd, gscale, first = (
        scalars[i] for i in range(6))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * gscale
    m = m_ref[:].astype(jnp.float32)

    if not wd_after_momentum:
        g = g + wd * p
    # first step: momentum buffer initialized to the raw gradient (PyTorch
    # semantics the reference's `first_run` flag reproduces). Runtime scalar
    # so the step counter stays traced.
    m = jnp.where(first > 0.5, g, momentum * m + (1.0 - dampening) * g)
    upd = (g + momentum * m) if nesterov else m
    if wd_after_momentum:
        upd = upd + wd * p
    p = p - lr * upd

    po_ref[:] = p.astype(po_ref.dtype)
    mo_ref[:] = m.astype(mo_ref.dtype)
    if has_copy:
        copy_ref[0][:] = p.astype(copy_ref[0].dtype)


def sgd_update(p, g, m, *, lr, momentum=0.0, dampening=0.0, weight_decay=0.0,
               nesterov=False, first_run=False, wd_after_momentum=False,
               grad_scale=1.0, param_copy_dtype=None):
    """Fused SGD with momentum. ``first_run`` (traced or static) initializes
    the momentum buffer inside the kernel (`fused_sgd.py:128-216`
    semantics). The optional ``param_copy_dtype`` output is the depth-4 mode
    (master step + model copy in one pass)."""
    first = jnp.asarray(first_run, jnp.float32)
    scalars = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                         (lr, momentum, dampening, weight_decay, grad_scale)]
                        + [first])
    out_dtypes = [p.dtype, m.dtype]
    if param_copy_dtype is not None:
        out_dtypes.append(jnp.dtype(param_copy_dtype))
    kernel = functools.partial(_sgd_kernel, nesterov, wd_after_momentum,
                               param_copy_dtype is not None)
    return _launch(kernel, [p, g, m], out_dtypes, scalars)


# --- Adagrad (`multi_tensor_adagrad.cu`) ------------------------------------

def _adagrad_kernel(adagrad_w, scalars, p_ref, g_ref, h_ref, po_ref, ho_ref):
    lr, eps, wd, gscale = (scalars[i] for i in range(4))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * gscale
    h = h_ref[:].astype(jnp.float32)

    if not adagrad_w:
        g = g + wd * p
    h = h + g * g
    upd = g / (jnp.sqrt(h) + eps)
    if adagrad_w:            # decoupled decay
        upd = upd + wd * p
    p = p - lr * upd

    po_ref[:] = p.astype(po_ref.dtype)
    ho_ref[:] = h.astype(ho_ref.dtype)


def adagrad_update(p, g, h, *, lr, eps=1e-10, weight_decay=0.0,
                   adagrad_w_mode=False, grad_scale=1.0):
    scalars = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                         (lr, eps, weight_decay, grad_scale)])
    kernel = functools.partial(_adagrad_kernel, adagrad_w_mode)
    return _launch(kernel, [p, g, h], [p.dtype, h.dtype], scalars)


# --- LAMB, two-stage (`multi_tensor_lamb.cu:41,234`) ------------------------

def _lamb_stage1_kernel(adam_w, scalars, p_ref, g_ref, m_ref, v_ref,
                        u_ref, mo_ref, vo_ref):
    b1, b2, eps, wd, bc1, bc2, clip, b3 = (scalars[i] for i in range(8))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * clip   # global-norm clip folded in
    m = m_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    if not adam_w:
        g = g + wd * p
    m = b1 * m + b3 * g
    v = b2 * v + (1.0 - b2) * g * g
    u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w:
        u = u + wd * p

    u_ref[:] = u
    mo_ref[:] = m.astype(mo_ref.dtype)
    vo_ref[:] = v.astype(vo_ref.dtype)


def lamb_stage1(p, g, m, v, *, beta1, beta2, eps, weight_decay, step,
                bias_correction=True, adam_w_mode=True, clip_scale=1.0,
                grad_averaging=True):
    """Stage 1: Adam-style update direction ``u`` (fp32) + new m, v.

    ``clip_scale`` pre-scales grads by ``max_grad_norm/global_norm`` when
    clipping is active (the reference computes the global norm with
    `multi_tensor_l2norm` first, `fused_lamb.py:120-136`).
    ``grad_averaging=False`` accumulates raw grads into the first moment
    (``m = β1·m + g`` instead of ``β1·m + (1−β1)·g``) — the reference's
    ``grad_averaging`` knob (`multi_tensor_lamb.cu:60-63`, the same
    ``beta3`` NovoGrad exposes)."""
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bc1 = 1.0 - jnp.power(jnp.float32(beta1), step)
        bc2 = 1.0 - jnp.power(jnp.float32(beta2), step)
    else:
        bc1 = bc2 = jnp.float32(1.0)
    b3 = (1.0 - beta1) if grad_averaging else 1.0
    scalars = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                         (beta1, beta2, eps, weight_decay, bc1, bc2,
                          clip_scale, b3)])
    kernel = functools.partial(_lamb_stage1_kernel, adam_w_mode)
    return _launch(kernel, [p, g, m, v],
                   [jnp.float32, m.dtype, v.dtype], scalars)


def _lamb_stage2_kernel(has_copy, scalars, p_ref, u_ref, r_ref,
                        po_ref, *copy_ref):
    lr = scalars[0]
    p = p_ref[:].astype(jnp.float32)
    u = u_ref[:]
    r = r_ref[:]                       # per-position trust ratio
    p = p - lr * r * u
    po_ref[:] = p.astype(po_ref.dtype)
    if has_copy:
        copy_ref[0][:] = p.astype(copy_ref[0].dtype)


def lamb_stage2(p, u, ratio_per_pos, *, lr, param_copy_dtype=None):
    """Stage 2: apply ``p -= lr * trust_ratio * u``; the trust ratio is
    gathered per arena position from per-tensor norms computed between the
    stages (`multi_tensor_lamb.cu:234-320`)."""
    scalars = jnp.stack([jnp.asarray(lr, jnp.float32)])
    out_dtypes = [p.dtype]
    if param_copy_dtype is not None:
        out_dtypes.append(jnp.dtype(param_copy_dtype))
    kernel = functools.partial(_lamb_stage2_kernel,
                               param_copy_dtype is not None)
    return _launch(kernel, [p, u, ratio_per_pos], out_dtypes, scalars)


# --- NovoGrad (`multi_tensor_novograd.cu:24-130`) ---------------------------

def _novograd_kernel(reg_inside_moment, scalars, p_ref, g_ref, m_ref,
                     vpos_ref, po_ref, mo_ref):
    lr, b1, b3, eps, wd, bc1, bc2 = (scalars[i] for i in range(7))
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32)
    vnorm = vpos_ref[:]                # per-position per-layer norm EMA

    denom = vnorm / bc2 + eps
    if reg_inside_moment:
        # MOMENT_MODE_0: normalize + decay inside the momentum
        g = g / denom + wd * p
        m = b1 * m + b3 * g
        p = p - lr * (m / bc1)
    else:
        # MOMENT_MODE_1 (reference default): raw-grad momentum, decoupled
        # decay at update time (`multi_tensor_novograd.cu:107-112`)
        m = b1 * m + b3 * g
        update = (m / bc1) / denom + wd * p
        p = p - lr * update
    po_ref[:] = p.astype(po_ref.dtype)
    mo_ref[:] = m.astype(mo_ref.dtype)


def novograd_update(p, g, m, vnorm_per_pos, *, lr, beta1, beta2, eps,
                    weight_decay, step, grad_averaging=True,
                    bias_correction=True, reg_inside_moment=False):
    """NovoGrad elementwise stage. The per-layer norm EMAs (a
    (num_tensors,) vector — the reference's ``exp_avg_sq`` buffer, which
    stores *norms*, not squares, `fused_novograd.py:157-174`) are maintained
    outside and broadcast per position. bc2 = sqrt(1-beta2^t) matches the
    reference's correction of the norm (`multi_tensor_novograd.cu:148-152`)."""
    b3 = (1.0 - beta1) if grad_averaging else 1.0
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bc1 = 1.0 - jnp.power(jnp.float32(beta1), step)
        bc2 = jnp.sqrt(1.0 - jnp.power(jnp.float32(beta2), step))
    else:
        bc1 = bc2 = jnp.float32(1.0)
    scalars = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                         (lr, beta1, b3, eps, weight_decay)] + [bc1, bc2])
    kernel = functools.partial(_novograd_kernel, reg_inside_moment)
    return _launch(kernel, [p, g, m, vnorm_per_pos],
                   [p.dtype, m.dtype], scalars)
