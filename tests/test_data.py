"""Input pipeline: ImageFolder decode/augment source + device prefetch.

The reference's loader behavior (`examples/imagenet/main_amp.py:28-57`,
data_prefetcher `:264-317`) — shapes, label mapping, epoch reshuffle,
prefetch overlap and error propagation — on a generated JPEG tree.
"""

import numpy as np
import pytest

pytest.importorskip("PIL")

from apex_tpu.data import (DevicePrefetcher, ImageFolderSource,
                           make_fake_imagefolder, measure_source,
                           synthetic_source)


@pytest.fixture(scope="module")
def tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("fakeimagenet")
    return make_fake_imagefolder(str(root), n_classes=3, per_class=4,
                                 size=64)


def test_imagefolder_batches(tree):
    src = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=0)
    assert len(src.classes) == 3
    batches = list(src.epoch())
    assert len(batches) == 3          # 12 images / 4, drop_last
    for x, y in batches:
        assert x.shape == (4, 32, 32, 3) and x.dtype == np.float32
        assert x.min() >= 0.0 and x.max() <= 1.0
        assert y.dtype == np.int32 and set(y) <= {0, 1, 2}


def test_epochs_reshuffle_and_steps(tree):
    src = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=0)
    a = [y.tolist() for _, y in src.epoch()]
    b = [y.tolist() for _, y in src.epoch()]
    assert a != b                      # per-epoch reshuffle
    n = sum(1 for _ in src.batches(7))
    assert n == 7                      # crosses the epoch boundary


def test_eval_transform_deterministic(tree):
    src = ImageFolderSource(tree, batch=4, size=32, workers=2,
                            train=False, seed=0)
    x1, _ = next(src.epoch())
    src2 = ImageFolderSource(tree, batch=4, size=32, workers=2,
                             train=False, seed=0)
    x2, _ = next(src2.epoch())
    np.testing.assert_array_equal(x1, x2)  # center crop, no augment


def test_device_prefetcher_order_and_cast():
    import jax.numpy as jnp

    src = synthetic_source(2, 8, 5, seed=3)
    got = list(DevicePrefetcher(src, cast_dtype=jnp.bfloat16, depth=2))
    assert len(got) == 5
    assert got[0][0].dtype == jnp.bfloat16
    want = list(synthetic_source(2, 8, 5, seed=3))
    np.testing.assert_allclose(np.asarray(got[0][0], np.float32),
                               want[0][0], atol=1e-2)
    np.testing.assert_array_equal(np.asarray(got[-1][1]), want[-1][1])


def test_device_prefetcher_propagates_errors():
    def bad():
        yield np.zeros((1, 2, 2, 3), np.float32), np.zeros(1, np.int32)
        raise ValueError("decode failed")

    pre = DevicePrefetcher(bad())
    it = iter(pre)
    next(it)
    with pytest.raises(ValueError, match="decode failed"):
        list(it)


def test_measure_source_runs(tree):
    src = ImageFolderSource(tree, batch=4, size=32, workers=2)
    rate = measure_source(src.batches(4), steps=3)
    assert rate > 0


def test_too_small_dataset_raises(tmp_path):
    make_fake_imagefolder(str(tmp_path), n_classes=1, per_class=2, size=32)
    src = ImageFolderSource(str(tmp_path), batch=8, size=16, workers=1)
    with pytest.raises(ValueError, match="no batch"):
        next(src.batches(1))


# --- per-process file shards + the resumable cursor (ROADMAP 5b) ------------


def test_process_file_shards_are_disjoint_and_cover(tree):
    """Ranks never read overlapping files, and together they cover the
    whole dataset — the no-duplicate-decode contract."""
    full = ImageFolderSource(tree, batch=1, size=16, workers=1)
    shards = [ImageFolderSource(tree, batch=1, size=16, workers=1,
                                process_index=r, process_count=3)
              for r in range(3)]
    sets = [set(s.paths) for s in shards]
    for i in range(3):
        for j in range(i + 1, 3):
            assert not (sets[i] & sets[j]), (i, j)
    assert set().union(*sets) == set(full.paths)   # 12 % 3 == 0
    # labels follow their files through the shard slice
    for s in shards:
        for p, l in zip(s.paths, s.labels):
            k = full.paths.index(p)
            assert full.labels[k] == l
    # non-divisible case: shards are EQUALIZED (same batch count per
    # rank → lockstep collectives never desync at the epoch tail); the
    # <world remainder is dropped, not assigned lopsidedly
    uneven = [ImageFolderSource(tree, batch=1, size=16, workers=1,
                                process_index=r, process_count=5)
              for r in range(5)]
    ns = [len(s.paths) for s in uneven]
    assert len(set(ns)) == 1 and ns[0] == len(full.paths) // 5
    got = set().union(*(set(s.paths) for s in uneven))
    assert len(set(full.paths) - got) == len(full.paths) % 5


def test_shard_rank_out_of_range_and_empty_raise(tree, tmp_path):
    with pytest.raises(ValueError, match="out of range"):
        ImageFolderSource(tree, batch=1, size=16, workers=1,
                          process_index=3, process_count=3)
    make_fake_imagefolder(str(tmp_path / "tiny"), n_classes=1,
                          per_class=2, size=32)
    with pytest.raises(ValueError, match="empty file shard"):
        ImageFolderSource(str(tmp_path / "tiny"), batch=1, size=16,
                          workers=1, process_index=5, process_count=9)


def test_cursor_resume_is_exact(tree):
    """The checkpoint contract: a source resumed from a cursor yields
    the exact remaining stream — batches bitwise-equal to the
    uninterrupted run, across an epoch boundary."""
    ref = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=5)
    stream = [(x.copy(), y.copy()) for x, y in ref.batches(5)]

    src = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=5)
    it = src.batches(5)
    for k in range(2):
        next(it)
    cursor = src.state()
    it.close()

    resumed = ImageFolderSource(tree, batch=4, size=32, workers=2,
                                seed=5).load_state(cursor)
    rest = [(x, y) for x, y in resumed.batches(3)]
    assert len(rest) == 3
    for (xa, ya), (xb, yb) in zip(stream[2:], rest):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_skip_batches_fast_forward_is_exact(tree):
    """The guard's poison-batch skip: a zero-decode skip_batches(n)
    lands on the exact same stream as consuming n batches — including
    from the post-epoch transient cursor (batch == batches_per_epoch,
    captured right after an epoch's last yielded batch), where an
    increment-then-wrap skip would swallow one batch and land a rewind
    one short of the offending window's end."""
    ref = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=5)
    stream = [(x.copy(), y.copy()) for x, y in ref.batches(7)]

    # plain mid-epoch skip
    src = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=5)
    next(src.batches(1))
    src.skip_batches(3)
    assert src.cursor_index() == 4
    x, y = next(src.batches(1))
    np.testing.assert_array_equal(x, stream[4][0])

    # post-epoch transient: consume a FULL epoch via a live generator
    # (cursor records batch == 3 == batches_per_epoch), then skip
    src = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=5)
    it = src.epoch()
    for _ in range(len(src)):
        next(it)
    cursor = src.state()
    assert cursor["batch"] == len(src)       # the transient state
    resumed = ImageFolderSource(tree, batch=4, size=32, workers=2,
                                seed=5).load_state(cursor)
    resumed.skip_batches(2)
    assert resumed.cursor_index() == len(src) + 2
    x, y = next(resumed.batches(1))
    np.testing.assert_array_equal(x, stream[len(src) + 2][0])


def test_cursor_mismatch_is_refused(tree):
    src = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=5)
    cursor = src.state()
    other = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=6)
    with pytest.raises(ValueError, match="cursor mismatch"):
        other.load_state(cursor)
    # a different batch geometry shifts where batch index k starts —
    # resuming would double-/skip-read, so it must be refused too
    other_batch = ImageFolderSource(tree, batch=2, size=32, workers=2,
                                    seed=5)
    with pytest.raises(ValueError, match="batch_size"):
        other_batch.load_state(cursor)


def test_cursor_json_roundtrips(tree):
    """The cursor must survive the checkpoint manifest (JSON)."""
    import json

    src = ImageFolderSource(tree, batch=4, size=32, workers=2, seed=1)
    next(src.batches(1))
    cur = json.loads(json.dumps(src.state()))
    src2 = ImageFolderSource(tree, batch=4, size=32, workers=2,
                             seed=1).load_state(cur)
    assert src2.state() == src.state()


# --- packed pre-decoded cache (the DALI-class path) -------------------------

from apex_tpu.data import PackedSource, build_cache


@pytest.fixture(scope="module")
def cache(tree, tmp_path_factory):
    cdir = tmp_path_factory.mktemp("packedcache")
    return build_cache(tree, str(cdir), store_size=48, shard_images=5)


def test_build_cache_layout_and_idempotence(tree, cache):
    import json, os
    with open(os.path.join(cache, "meta.json")) as f:
        meta = json.load(f)
    assert meta["n"] == 12 and meta["store_size"] == 48
    assert [s["n"] for s in meta["shards"]] == [5, 5, 2]
    labels = np.load(os.path.join(cache, "labels.npy"))
    assert labels.shape == (12,) and set(labels) == {0, 1, 2}
    # second build with matching meta is a no-op (same mtimes)
    m0 = os.path.getmtime(os.path.join(cache, "shard_00000.npy"))
    build_cache(tree, cache, store_size=48)
    assert os.path.getmtime(
        os.path.join(cache, "shard_00000.npy")) == m0


def test_build_cache_rebuilds_on_content_change(tmp_path):
    """Same file count, changed content → fingerprint mismatch forces a
    rebuild (ADVICE r4: count+size reuse served stale pixels)."""
    import json, os
    root = make_fake_imagefolder(str(tmp_path / "imgs"), n_classes=2,
                                 per_class=3, size=64)
    cdir = str(tmp_path / "cache")
    build_cache(root, cdir, store_size=48, shard_images=4)
    meta_path = os.path.join(cdir, "meta.json")
    with open(meta_path) as f:
        fp0 = json.load(f)["fingerprint"]
    # rename one class dir: same count, different path list + labels
    cls = sorted(os.listdir(root))[0]
    os.rename(os.path.join(root, cls), os.path.join(root, "zzz_" + cls))
    build_cache(root, cdir, store_size=48, shard_images=4)
    with open(meta_path) as f:
        fp1 = json.load(f)["fingerprint"]
    assert fp1 != fp0

    # in-place edit: same paths and labels, touched mtime → rebuild
    cls0 = sorted(os.listdir(root))[0]
    img0 = os.path.join(root, cls0,
                        sorted(os.listdir(os.path.join(root, cls0)))[0])
    os.utime(img0, ns=(os.stat(img0).st_atime_ns,
                       os.stat(img0).st_mtime_ns + 10**9))
    build_cache(root, cdir, store_size=48, shard_images=4)
    with open(meta_path) as f:
        assert json.load(f)["fingerprint"] != fp1


def test_packed_source_batches_and_labels(cache):
    with PackedSource(cache, batch=4, size=32, seed=0) as src:
        assert len(src) == 3
        for x, y in src.epoch():
            assert x.shape == (4, 32, 32, 3) and x.dtype == np.float32
            assert x.min() >= 0.0 and x.max() < 1.0
            assert y.dtype == np.int32


def test_packed_uint8_matches_float_path(cache):
    """Raw uint8 mode must be the float batches before the 1/255 scale
    (same seed → same crops/flips)."""
    with PackedSource(cache, 4, 32, seed=5) as a, \
            PackedSource(cache, 4, 32, seed=5, dtype=np.uint8) as b:
        xf, yf = next(a.epoch())
        xu, yu = next(b.epoch())
    np.testing.assert_array_equal(yf, yu)
    np.testing.assert_allclose(xf, xu.astype(np.float32) / 255.0,
                               atol=1e-7)


def test_packed_eval_is_center_crop(cache):
    """Eval mode: deterministic center crop straight from the shard."""
    with PackedSource(cache, 4, 32, train=False, seed=0) as src:
        x1, _ = next(src.epoch())
    with PackedSource(cache, 4, 32, train=False, seed=0) as src2:
        x2, _ = next(src2.epoch())
    np.testing.assert_array_equal(x1, x2)


def test_packed_epochs_reshuffle(cache):
    with PackedSource(cache, 4, 32, seed=1, dtype=np.uint8) as src:
        e1 = [y.tolist() for _, y in src.epoch()]
        e2 = [y.tolist() for _, y in src.epoch()]
    assert e1 != e2   # 12! orderings; same would be a frozen shuffle


def test_packed_rrc_mode_runs(cache):
    with PackedSource(cache, 4, 32, seed=2, rrc=True) as src:
        x, y = next(src.epoch())
        assert x.shape == (4, 32, 32, 3)


def test_packed_crop_larger_than_store_raises(cache):
    with pytest.raises(ValueError):
        PackedSource(cache, 4, 64)


def test_packed_source_through_prefetcher(cache):
    import jax.numpy as jnp
    with PackedSource(cache, 4, 32, seed=3, dtype=np.uint8) as src:
        pre = DevicePrefetcher(src.batches(3))
        got = list(pre)
    assert len(got) == 3
    assert got[0][0].dtype == jnp.uint8
