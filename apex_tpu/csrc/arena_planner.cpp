// Arena layout planner — native host-side bookkeeping for the flat
// parameter arena (apex_tpu.arena).
//
// TPU-native counterpart of the reference's apex_C native module
// (csrc/flatten_unflatten.cpp:15-17): where apex_C packs CUDA tensor lists
// into flat buffers for DDP buckets, this planner computes the aligned
// slot layout (offsets, padded sizes, bucket boundaries) that the JAX-side
// flatten/unflatten and the Pallas multi-tensor kernels consume. The device
// copies themselves are XLA's job; the layout math is host-native.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
//
// Build: make -C apex_tpu/csrc  ->  apex_tpu/_native/libapex_tpu.so

#include <cstdint>
#include <cstring>

extern "C" {

// Compute aligned offsets for n tensors of the given element counts.
//
//  sizes[n]     : element count per tensor
//  alignment    : slot alignment in elements (power of two, e.g. 1024 so a
//                 flat buffer reshaped to (-1, 128) keeps every tensor
//                 starting on an (8,128) fp32 tile boundary)
//  offsets[n]   : out — start offset of each tensor slot
//  padded[n]    : out — aligned slot size of each tensor
//  returns      : total arena size in elements (aligned)
int64_t apex_plan_layout(int64_t n, const int64_t* sizes, int64_t alignment,
                         int64_t* offsets, int64_t* padded) {
  if (alignment <= 0) alignment = 1;
  int64_t cursor = 0;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = cursor;
    int64_t p = (sizes[i] + alignment - 1) / alignment * alignment;
    padded[i] = p;
    cursor += p;
  }
  return cursor;
}

// Greedy bucket assignment by cumulative slot size — the layout-time
// analogue of DDP's message_size bucketing (the reference builds buckets
// from backward arrival order and broadcasts rank 0's structure,
// apex/parallel/distributed.py:363-394; with XLA the order is static so
// buckets are a pure function of the layout).
//
//  padded[n]       : aligned slot sizes (from apex_plan_layout)
//  bucket_elems    : target bucket size in elements (message_size)
//  bucket_ids[n]   : out — bucket index per tensor (monotone)
//  returns         : number of buckets
int64_t apex_plan_buckets(int64_t n, const int64_t* padded,
                          int64_t bucket_elems, int64_t* bucket_ids) {
  if (bucket_elems <= 0) bucket_elems = 1;
  int64_t bucket = 0, fill = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (fill > 0 && fill + padded[i] > bucket_elems) {
      ++bucket;
      fill = 0;
    }
    bucket_ids[i] = bucket;
    fill += padded[i];
  }
  return n > 0 ? bucket + 1 : 0;
}

// Partition a flat arena of total_elems into world_size equal shards,
// aligned so every shard boundary falls on `alignment` elements — the
// ZeRO-1 shard map (reference: 128-byte aligned block/chunk/shard split,
// apex/contrib/optimizers/distributed_fused_adam.py:99-148).
//
//  returns shard size in elements (total padded up as needed);
//  shard_starts[world_size] receives each shard's start offset.
int64_t apex_plan_shards(int64_t total_elems, int64_t world_size,
                         int64_t alignment, int64_t* shard_starts) {
  if (world_size <= 0) return 0;
  if (alignment <= 0) alignment = 1;
  int64_t per = (total_elems + world_size - 1) / world_size;
  per = (per + alignment - 1) / alignment * alignment;
  for (int64_t i = 0; i < world_size; ++i) shard_starts[i] = i * per;
  return per;
}

int64_t apex_native_abi_version() { return 1; }

}  // extern "C"
