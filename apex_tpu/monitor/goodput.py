"""Goodput ledger: where each step's wall clock actually went.

The observability stack proves *structure* (zero extra dispatches, HLO
byte accounting, retrace counts) but structure doesn't say which
milliseconds a step spent computing versus waiting. This module is the
runtime time-attribution half: it joins the host span timeline the
:class:`apex_tpu.trace.Tracer` already records — including the
back-dated ``kind="compile"`` spans :mod:`apex_tpu.prof.compile_watch`
injects, checkpoint ``stall_ms`` from the ckpt event channel, and
guard action events — into one per-step ledger of named buckets:

======================  ======================================================
bucket                  what lands in it
======================  ======================================================
``compute``             dispatch + device wait of the step program itself
``comm_skew``           wait-for-laggard share of exposed collectives —
                        joined from the pod observatory's cross-rank
                        entry-skew measurement (``note_pod_skew``; zero
                        without pod data)
``comm_wire``           host spans tagged ``kind="collective"`` (a collective
                        the scheduler could not hide behind compute), minus
                        any joined skew — the share the fabric actually took
``input_wait``          data loading / host input spans (``data/*``,
                        ``input/*``, ``load*``)
``host_callback``       host fetches and callbacks (``fetch*``, ``host/*``,
                        ``callback/*``) — the sync points
``ckpt_stall``          checkpoint capture stall joined from ``ckpt_save``
                        events (``note_ckpt``) plus ``ckpt/*`` spans
``recompile``           ``kind="compile"`` spans (retraces, autotune)
``guard_rewind``        guard intervention wall time joined from guard
                        action/rewind events (``note_guard``) + ``guard/*``
``other``               wall time no span covered (the residual)
======================  ======================================================

Attribution is a sweep over the step's span intervals — at every
instant exactly one bucket owns the clock (the deepest covering span
wins), so nested and overlapping spans never double-count and the
bucket sum **closes over the measured step wall time** by construction;
:meth:`GoodputLedger.check_closure` asserts the closure within a stated
tolerance, memory_budget-style (``scripts/goodput_audit.py --cpu8``
pins 5% in CI).

The two exposed-communication buckets additionally carry a **per-axis
split** (:attr:`StepLedger.comm_axes_ms`): each collective span's name
is joined through the planned-collective registry
(:func:`apex_tpu.monitor.collectives.scope_axis_row`), so the ledger
can say "zero axis cost 0.8 ms exposed, dp axis 0.3 ms" per step —
unregistered scopes land in an explicit ``"unknown"`` row, and the
axis sums equal the buckets exactly (docs/monitoring.md#per-axis).

**Goodput fraction** = useful-step time ÷ wall time, where useful =
the ``compute`` bucket (everything else is overhead some subsystem can
shrink). :meth:`rolling_goodput` averages it over a window;
:meth:`table` renders the per-step ledger; :meth:`to_events` emits
``kind="goodput"`` JSONL events for the
``MetricsLogger(goodput_sink=...)`` channel
(``scripts/check_metrics_schema.py --kind goodput`` validates).

Typical wiring::

    tracer = trace.Tracer()
    ledger = monitor.GoodputLedger(tracer)      # subscribes to steps
    logger = monitor.MetricsLogger(goodput_sink=monitor.JSONLSink(p))
    ledger.subscribe(logger.record_goodput)     # stream per-step events
    mgr = ckpt.CheckpointManager(root, event_sink=lambda ev: (
        logger.record_ckpt(ev), ledger.note_ckpt(ev)))
    with tracer:
        for i, batch in enumerate(data):
            with trace.step(i):
                with trace.span("dispatch"):
                    state, loss = train_step(state, batch)
                with trace.span("fetch"):
                    logger.record(state.metrics)
    print(ledger.table())
    print(f"goodput {ledger.rolling_goodput():.1%}")

Purely host-side: the ledger reads finished
:class:`~apex_tpu.trace.StepTrace` records, never the device — the
instrumented step compiles bit-identical HLO (the
``goodput/no-extra-dispatch`` compile-check case pins it).
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["BUCKETS", "GoodputLedger", "StepLedger", "classify_span"]

#: the ledger's bucket names, report order. ``compute`` is the goodput
#: numerator; ``other`` is the residual no span covered. ``comm_skew``
#: + ``comm_wire`` together are the exposed-communication time the
#: pre-podview ledger reported as one ``exposed_comm`` bucket
#: (:attr:`StepLedger.exposed_comm` keeps that sum readable).
BUCKETS = ("compute", "comm_skew", "comm_wire", "input_wait",
           "host_callback", "ckpt_stall", "recompile", "guard_rewind",
           "other")

#: span-name prefixes per bucket (checked before the kind rules; first
#: match wins, longest prefix first at classify time)
_NAME_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("data/", "input_wait"), ("input/", "input_wait"),
    ("load", "input_wait"),
    ("fetch", "host_callback"), ("host/", "host_callback"),
    ("callback/", "host_callback"),
    ("ckpt/", "ckpt_stall"),
    ("guard/", "guard_rewind"),
    ("compile/", "recompile"),
)


def classify_span(name: str, kind: str = "span") -> str:
    """Bucket for one span: the span ``kind`` ("collective"/"compile")
    takes precedence, then the name-prefix table, else ``compute``."""
    if kind == "collective":
        # the span sweep cannot see cross-rank entry skew; collective
        # time lands in comm_wire and note_pod_skew moves the measured
        # wait-for-laggard share to comm_skew after the fact
        return "comm_wire"
    if kind == "compile":
        return "recompile"
    for prefix, bucket in _NAME_PREFIXES:
        if name.startswith(prefix):
            return bucket
    return "compute"


class StepLedger:
    """One step's attribution: wall time + per-bucket milliseconds."""

    __slots__ = ("step", "wall_ms", "buckets", "wall_time",
                 "comm_axes_ms")

    def __init__(self, step: Optional[int], wall_ms: float,
                 buckets: Dict[str, float],
                 comm_axes_ms: Optional[Dict[str, Dict[str, float]]]
                 = None):
        self.step = step
        self.wall_ms = wall_ms
        self.buckets = buckets        # {bucket: ms}, every BUCKETS key
        #: per-mesh-axis split of the exposed-communication buckets:
        #: ``{axis: {"wire": ms, "skew": ms}}`` — axes joined from each
        #: collective span's scope through the planned-collective
        #: registry (scope_axis_row; unregistered scopes land in
        #: ``"unknown"``). The axis sums equal the comm_wire/comm_skew
        #: buckets by construction.
        self.comm_axes_ms = comm_axes_ms or {}
        self.wall_time = time.time()

    @property
    def attributed_ms(self) -> float:
        """Span-covered milliseconds (everything but ``other``)."""
        return sum(v for k, v in self.buckets.items() if k != "other")

    @property
    def exposed_comm(self) -> float:
        """Total exposed-collective milliseconds — the pre-podview
        single bucket, now the ``comm_skew + comm_wire`` sum."""
        return self.buckets["comm_skew"] + self.buckets["comm_wire"]

    @property
    def goodput_frac(self) -> Optional[float]:
        if not self.wall_ms or self.wall_ms <= 0:
            return None
        return self.buckets["compute"] / self.wall_ms

    def closure_error(self) -> float:
        """Relative attribution-closure error: |sum(buckets) − wall| /
        wall. ``other`` absorbs uncovered time, so the error is exactly
        the OVER-attribution a double count would introduce."""
        if not self.wall_ms or self.wall_ms <= 0:
            return 0.0
        return abs(sum(self.buckets.values()) - self.wall_ms) \
            / self.wall_ms

    def to_event(self, rank: int = 0) -> Dict:
        gf = self.goodput_frac
        return {"kind": "goodput", "step": self.step, "rank": rank,
                "wall_ms": round(self.wall_ms, 4),
                "buckets_ms": {k: round(v, 4)
                               for k, v in self.buckets.items()},
                "comm_axes_ms": {
                    ax: {k: round(v, 4) for k, v in parts.items()}
                    for ax, parts in self.comm_axes_ms.items()},
                "goodput_frac": round(gf, 6) if gf is not None else None,
                "closure_err": round(self.closure_error(), 6),
                "wall_time": self.wall_time}


def _attribute(spans, wall_ms: float,
               classify: Callable[[str, str], str]
               ) -> Tuple[Dict[str, float], Dict[str, float]]:
    """Sweep a step's span intervals into bucket milliseconds.

    Boundary sweep: between any two adjacent span boundaries exactly
    one span owns the clock — the deepest covering one (ties: the
    latest-starting, i.e. the one entered last) — so nesting and the
    back-dated compile spans :func:`Tracer.add_span_event` injects can
    never double-count an instant. Uncovered time is NOT emitted here;
    the caller assigns ``wall − covered`` to ``other``.

    Returns ``(buckets, comm_axis_ms)``: the second dict splits the
    ``comm_wire`` bucket per mesh axis by joining each winning
    collective span's name through the planned-collective registry
    (:func:`apex_tpu.monitor.collectives.scope_axis_row` — the one
    shared join; unregistered scopes land in ``"unknown"``), so
    ``sum(comm_axis_ms.values()) == buckets["comm_wire"]`` exactly.
    """
    out = {b: 0.0 for b in BUCKETS}
    axes: Dict[str, float] = {}
    if not spans:
        return out, axes
    from apex_tpu.monitor.collectives import scope_axis_row
    # (t0, t1, depth, order, bucket, name) in step-relative ms
    base = min(s.t_start for s in spans)
    ivals = []
    for order, s in enumerate(spans):
        t0 = (s.t_start - base) * 1e3
        ivals.append((t0, t0 + max(s.dur_ms, 0.0), s.depth, order,
                      classify(s.name, s.kind), s.name))
    bounds = sorted({b for iv in ivals for b in iv[:2]})
    for lo, hi in zip(bounds, bounds[1:]):
        if hi <= lo:
            continue
        covering = [iv for iv in ivals if iv[0] <= lo and iv[1] >= hi]
        if not covering:
            continue
        win = max(covering, key=lambda iv: (iv[2], iv[3]))
        bucket, name = win[4], win[5]
        out[bucket] += hi - lo
        if bucket == "comm_wire":
            ax = scope_axis_row(name)
            axes[ax] = axes.get(ax, 0.0) + (hi - lo)
    return out, axes


class GoodputLedger:
    """Per-step wall-time decomposition + rolling goodput fraction.

    Subscribe it to a :class:`apex_tpu.trace.Tracer` (pass the tracer,
    or call :meth:`on_step` yourself) and join the other event channels
    through :meth:`note_ckpt` / :meth:`note_guard`. ``subscribe``
    callbacks receive each finished step's ``kind="goodput"`` event —
    wire :meth:`apex_tpu.monitor.MetricsLogger.record_goodput` there.
    ``max_steps`` bounds the retained ledger like the Tracer's
    timeline.
    """

    def __init__(self, tracer=None, *, window: int = 50,
                 tolerance: float = 0.05, max_steps: int = 1024,
                 classify: Callable[[str, str], str] = classify_span,
                 rank: Optional[int] = None):
        self.window = max(int(window), 1)
        self.tolerance = float(tolerance)
        self.max_steps = max(int(max_steps), 1)
        self.classify = classify
        if rank is None:
            try:
                import jax
                rank = jax.process_index()
            except Exception:
                rank = 0
        self.rank = rank
        self.steps: List[StepLedger] = []
        self._on_step: List[Callable[[Dict], None]] = []
        self._frac = collections.deque(maxlen=self.window)
        # stalls joined from event channels, waiting for their step:
        # {step (or None=next): ms}
        self._pending: Dict[str, Dict] = {"ckpt_stall": {},
                                          "guard_rewind": {},
                                          "comm_skew": {}}
        if tracer is not None:
            tracer.subscribe(self.on_step)

    def subscribe(self, fn: Callable[[Dict], None]) -> None:
        self._on_step.append(fn)

    # -- event-channel joins --------------------------------------------------

    def _note(self, bucket: str, ms: float, step: Optional[int]) -> None:
        if ms is None or ms <= 0:
            return
        pend = self._pending[bucket]
        pend[step] = pend.get(step, 0.0) + float(ms)

    def note_ckpt(self, event: Dict) -> None:
        """Join one ``ckpt_save`` event's capture ``stall_ms`` into the
        matching step's ``ckpt_stall`` bucket (pass the same events the
        ``MetricsLogger(ckpt_sink=)`` channel gets — wire the
        CheckpointManager's ``event_sink`` to both). Events for steps
        already folded attach to the next finished step instead, so a
        post-step save is never lost."""
        if event.get("kind") != "ckpt_save":
            return
        self._note("ckpt_stall", event.get("stall_ms") or 0.0,
                   event.get("step"))

    def note_guard(self, event: Dict) -> None:
        """Join one guard event (``guard_action``/``guard_rewind``) —
        its host-side ``dur_ms`` (rewind restore time, when the policy
        recorded one) lands in ``guard_rewind``; events without a
        duration still mark the step (0 ms — the in-graph skip costs no
        wall time by design)."""
        if event.get("kind") not in ("guard_action", "guard_rewind"):
            return
        self._note("guard_rewind", event.get("dur_ms") or 0.0,
                   event.get("step"))

    def note_pod_skew(self, skew_ms: float,
                      step: Optional[int] = None) -> None:
        """Join this rank's pod-measured wait-for-laggard milliseconds
        (``PodTimeline.rank_step_skew()[rank, step]``) into the
        matching step's ``comm_skew`` bucket. The move comes OUT of
        ``comm_wire`` only (a skew claim larger than the measured
        collective time is clamped — pod blame can reclassify exposed
        collective time, never invent it), so the bucket sum still
        closes over wall time exactly."""
        self._note("comm_skew", skew_ms, step)

    def _take_pending(self, bucket: str, step: Optional[int]) -> float:
        pend = self._pending[bucket]
        ms = pend.pop(step, 0.0) if step is not None else 0.0
        # stale entries for already-folded steps attach here rather
        # than leak: anything keyed at or before this step, or unkeyed
        for k in list(pend):
            if k is None or (step is not None and isinstance(k, int)
                             and k <= step):
                ms += pend.pop(k)
        return ms

    # -- the fold -------------------------------------------------------------

    def on_step(self, st) -> None:
        """Tracer subscriber: fold one finished
        :class:`~apex_tpu.trace.StepTrace` into the ledger."""
        wall = st.dur_ms if st.dur_ms is not None else 0.0
        buckets, axis_wire = _attribute(st.spans, wall, self.classify)
        covered = sum(buckets.values())
        buckets["other"] += max(wall - covered, 0.0)
        skew_moved = 0.0
        for bucket, donors in (("ckpt_stall", ("other", "compute")),
                               ("guard_rewind", ("other", "compute")),
                               # pod skew only reclassifies exposed
                               # collective time — see note_pod_skew
                               ("comm_skew", ("comm_wire",))):
            joined = self._take_pending(bucket, st.step)
            # a joined stall MOVES measured time, never invents it —
            # the sum still closes over wall. Drain the residual first:
            # a stall spent outside every span (the Snapshotter-capture
            # case) is sitting in `other` by construction, and only a
            # stall that overlapped the dispatch window should come out
            # of compute.
            for donor in donors:
                if joined <= 0:
                    break
                take = min(joined, buckets[donor])
                if take > 0:
                    buckets[donor] -= take
                    buckets[bucket] += take
                    joined -= take
                    if bucket == "comm_skew":
                        skew_moved += take
        # the per-axis view of the same move: pod skew reclassifies
        # each axis's wire share proportionally (no axis-resolved skew
        # measurement exists — blame follows the wire it delayed), so
        # the axis sums still equal the comm_wire/comm_skew buckets
        comm_axes: Dict[str, Dict[str, float]] = {}
        wire_total = sum(axis_wire.values())
        for ax, ms in axis_wire.items():
            share = (skew_moved * ms / wire_total) if wire_total else 0.0
            comm_axes[ax] = {"wire": ms - share, "skew": share}
        rec = StepLedger(st.step, wall, buckets, comm_axes)
        self.steps.append(rec)
        if len(self.steps) > self.max_steps:
            del self.steps[:len(self.steps) - self.max_steps]
        gf = rec.goodput_frac
        if gf is not None:
            self._frac.append(gf)
        ev = rec.to_event(self.rank)
        for fn in list(self._on_step):
            try:
                fn(dict(ev))
            except Exception:
                pass          # observers never break the train loop

    # -- reports --------------------------------------------------------------

    def rolling_goodput(self) -> Optional[float]:
        """Mean goodput fraction over the last ``window`` steps."""
        if not self._frac:
            return None
        return sum(self._frac) / len(self._frac)

    def check_closure(self, tolerance: Optional[float] = None,
                      skip_first: int = 0) -> Tuple[bool, float]:
        """(ok, worst_error): does every retained step's bucket sum
        close over its measured wall time within ``tolerance``?
        ``skip_first`` excludes warmup steps (step 0 folds the trace +
        compile; its compile span is back-dated into the step but the
        closure there is still exact — the knob exists for callers
        whose warmup spans *straddle* the step boundary)."""
        tol = self.tolerance if tolerance is None else float(tolerance)
        worst = 0.0
        for rec in self.steps[skip_first:]:
            worst = max(worst, rec.closure_error())
        return worst <= tol, worst

    def to_events(self, rank: Optional[int] = None) -> List[Dict]:
        """``kind="goodput"`` events for every retained step."""
        r = self.rank if rank is None else rank
        return [rec.to_event(r) for rec in self.steps]

    def totals(self) -> Dict[str, float]:
        """Summed per-bucket milliseconds over the retained ledger."""
        out = {b: 0.0 for b in BUCKETS}
        for rec in self.steps:
            for b, v in rec.buckets.items():
                out[b] += v
        return out

    def comm_axes_totals(self) -> Dict[str, Dict[str, float]]:
        """Summed per-axis exposed-comm milliseconds over the retained
        ledger: ``{axis: {"wire": ms, "skew": ms}}`` — the "zero axis
        cost 0.8 ms exposed, dp axis 0.3 ms" rollup."""
        out: Dict[str, Dict[str, float]] = {}
        for rec in self.steps:
            for ax, parts in rec.comm_axes_ms.items():
                slot = out.setdefault(ax, {"wire": 0.0, "skew": 0.0})
                for k, v in parts.items():
                    slot[k] = slot.get(k, 0.0) + v
        return out

    def table(self, width: int = 10) -> str:
        """Aligned per-step ledger: wall, every bucket, goodput%."""
        heads = ["step", "wall_ms"] + list(BUCKETS) + ["goodput"]
        lines = [" ".join(h[-width:].rjust(width) for h in heads)]
        for rec in self.steps:
            gf = rec.goodput_frac
            row = [str(rec.step if rec.step is not None else "-"),
                   f"{rec.wall_ms:.2f}"]
            row += [f"{rec.buckets[b]:.2f}" for b in BUCKETS]
            row.append(f"{gf:.1%}" if gf is not None else "n/a")
            lines.append(" ".join(v.rjust(width) for v in row))
        tot = self.totals()
        wall = sum(r.wall_ms for r in self.steps)
        row = ["total", f"{wall:.2f}"]
        row += [f"{tot[b]:.2f}" for b in BUCKETS]
        rg = self.rolling_goodput()
        row.append(f"{rg:.1%}" if rg is not None else "n/a")
        lines.append(" ".join(v.rjust(width) for v in row))
        axes = self.comm_axes_totals()
        if axes:
            lines.append("exposed comm by axis: " + "  ".join(
                f"{ax} wire {p['wire']:.2f} skew {p['skew']:.2f}"
                for ax, p in sorted(axes.items())))
        return "\n".join(lines)
