"""Multi-host bring-up — the `apex.parallel.multiproc` equivalent.

The reference launches one Python process per GPU with ``--rank i`` args
and env-var rendezvous (`apex/parallel/multiproc.py:1-35`,
`torch.distributed.launch`). On TPU pods the runtime already starts one
process per host; what remains is initializing the JAX distributed
client so every host sees the global device set. :func:`distributed_init`
wraps ``jax.distributed.initialize`` with the same env-var conventions
(`MASTER_ADDR``/``MASTER_PORT``/``RANK``/``WORLD_SIZE``) the reference's
launcher exports, so scripts written against either convention come up.

Single-host / single-process runs are a no-op — exactly like running a
reference script without the launcher.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["distributed_init", "is_distributed", "process_index",
           "process_count", "maybe_print", "enable_crash_dumps"]

_initialized = False


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None) -> None:
    """Initialize multi-host JAX, tolerating the reference's env vars.

    Resolution order per field: explicit argument → JAX's own env/TPU
    metadata (pass-through None) → the torch.distributed.launch
    convention (``MASTER_ADDR:MASTER_PORT``, ``WORLD_SIZE``, ``RANK``).
    Safe to call unconditionally: single-process (no env, no args) is a
    no-op, and repeat calls are ignored.
    """
    global _initialized
    if _initialized:
        return

    if coordinator_address is None and "MASTER_ADDR" in os.environ:
        port = os.environ.get("MASTER_PORT", "1234")
        coordinator_address = f"{os.environ['MASTER_ADDR']}:{port}"
    if num_processes is None and "WORLD_SIZE" in os.environ:
        num_processes = int(os.environ["WORLD_SIZE"])
    if process_id is None and "RANK" in os.environ:
        process_id = int(os.environ["RANK"])

    if (coordinator_address is None and num_processes is None
            and process_id is None
            and not os.environ.get("TPU_WORKER_HOSTNAMES")
            and not os.environ.get("COORDINATOR_ADDRESS")):
        return  # single process — nothing to initialize

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized = True


def enable_crash_dumps(path: str = "apex_tpu_crash.jsonl", *,
                       capacity: int = 64,
                       hang_deadline_s: Optional[float] = None):
    """One-call forensics bring-up for (multi-host) launches.

    Builds a :class:`apex_tpu.trace.Tracer`, a per-rank
    :class:`~apex_tpu.trace.FlightRecorder` (``path`` gets
    ``trace.rank_path`` applied on multi-process runs, so every rank of
    a pod dumps to its own file) with the excepthook/SIGTERM/atexit
    handlers installed, and — when ``hang_deadline_s`` is set — a
    started :class:`~apex_tpu.trace.HangWatchdog`. Call after
    :func:`distributed_init` so rank resolution sees the cluster.

    Returns ``(tracer, recorder, watchdog-or-None)``; enter the tracer
    around the train loop and wrap steps in ``trace.step()`` /
    ``trace.span`` so dumps carry span timelines (docs/tracing.md).
    """
    from apex_tpu import trace as _trace
    tracer = _trace.Tracer()
    recorder = _trace.FlightRecorder(path, capacity=capacity,
                                     tracer=tracer).install()
    watchdog = None
    if hang_deadline_s:
        watchdog = _trace.HangWatchdog(hang_deadline_s, recorder=recorder,
                                       tracer=tracer).start()
    return tracer, recorder, watchdog


def is_distributed() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


#: print verbosity, the `_amp_state.verbosity` knob
#: (`apex/amp/_amp_state.py:36-50`). 0 silences maybe_print entirely.
verbosity = 1


def maybe_print(msg: str, rank0: bool = False) -> None:
    """Verbosity- and rank-aware print (`_amp_state.maybe_print`,
    `apex/amp/_amp_state.py:38-50`)."""
    if verbosity <= 0:
        return
    if rank0 and jax.process_index() != 0:
        return
    print(msg)
