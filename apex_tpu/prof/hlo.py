"""Static HLO cost analysis — the pyprof.prof analyzer equivalent.

The reference computes per-op FLOPs/bytes/tensor-core eligibility from
recorded call shapes (`apex/pyprof/prof/prof.py:1-256`, `blas.py`,
`conv.py`). On TPU the compiler already knows: XLA's cost analysis reports
flops and bytes for the compiled executable, and the optimized HLO text
carries every fused instruction with layouts. This module exposes both —
an aggregate ``cost_analysis`` and a per-instruction ``op_estimates``
computed from the optimized HLO (dot/conv FLOPs from shapes, bytes from
operand/result sizes).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

__all__ = ["cost_analysis", "op_estimates", "op_estimates_from_text",
           "OpEstimate", "compiled_hlo", "iter_instructions"]

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f8e4m3": 1, "f8e5m2": 1, "bf16": 2, "f16": 2,
    "f32": 4, "f64": 8, "c64": 8, "c128": 16,
    # XLA's saturating/fnuz fp8 spellings (what optimized HLO actually
    # prints for float8_e4m3fn etc.) — absent entries would silently
    # zero fp8 wire/buffer bytes in memory and collective accounting
    "f8e4m3fn": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1, "f8e3m4": 1,
    "f8e4m3b11fnuz": 1,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _compile(fn, *args, **kwargs):
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return jitted.lower(*args, **kwargs).compile()


def compiled_hlo(fn, *args, **kwargs) -> str:
    """Optimized (post-fusion, post-layout) HLO text of the compiled fn."""
    return _compile(fn, *args, **kwargs).as_text()


def cost_analysis_of(compiled) -> Dict:
    """Raw cost-analysis dict of an already-compiled executable,
    normalized across jax versions (older jax returns one dict per
    device as a list)."""
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca


def cost_analysis(fn, *args, **kwargs) -> Dict[str, float]:
    """XLA's own executable cost analysis, normalized.

    Returns {"flops", "bytes_accessed", "optimal_seconds"} (missing keys
    0.0). ``fn`` may be a plain callable (jitted here), a jitted fn, or an
    already-lowered/compiled object's owner.
    """
    ca = cost_analysis_of(_compile(fn, *args, **kwargs))
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "optimal_seconds": float(ca.get("optimal_seconds", 0.0)),
    }


def _shape_elems_bytes(shape_text: str):
    """All (elems, bytes) for every typed shape in an HLO type string."""
    total_e, total_b = 0, 0
    for dt, dims in _SHAPE_RE.findall(shape_text):
        if dt not in _DTYPE_BYTES:
            continue
        elems = int(np.prod([int(d) for d in dims.split(",") if d] or [1]))
        total_e += elems
        total_b += elems * _DTYPE_BYTES[dt]
    return total_e, total_b


@dataclasses.dataclass
class OpEstimate:
    """Static per-instruction estimate from optimized HLO."""

    name: str
    opcode: str
    flops: float        # dot/conv only (0 for others — XLA fuses the rest)
    bytes: float        # operand + result bytes (HBM traffic upper bound)
    hlo: str


def _dims(shape_text: Optional[str]) -> Optional[List[int]]:
    if not shape_text:
        return None
    m = _SHAPE_RE.match(shape_text)
    if not m:
        return None
    return [int(d) for d in m.group(2).split(",") if d]


def _dot_flops(line: str, out_elems: int, operands: List[str],
               shapes: Dict[str, str]) -> float:
    """2*M*N*K for a dot; K from the lhs operand's contracting dims."""
    cdims = re.search(r"lhs_contracting_dims=\{([\d,]+)\}", line)
    ldims = _dims(shapes.get(operands[0])) if operands else None
    if not cdims or ldims is None:
        return 0.0
    k = int(np.prod([ldims[int(i)] for i in cdims.group(1).split(",")
                     if int(i) < len(ldims)] or [1]))
    return 2.0 * out_elems * k


def _conv_flops(line: str, out_elems: int, operands: List[str],
                shapes: Dict[str, str]) -> float:
    """2 * out_elems * (kernel_spatial * in_channels) for a convolution."""
    kdims = _dims(shapes.get(operands[1])) if len(operands) > 1 else None
    if kdims is None:
        return 0.0
    dnums = re.search(r"dim_labels=[\w?]+_([\w?]+)->", line)
    if dnums:
        # kernel labels like "01io": product of all dims except 'o'
        labels = dnums.group(1)
        per_out = int(np.prod([kdims[i] for i, c in enumerate(labels)
                               if c != "o" and i < len(kdims)] or [1]))
    else:
        per_out = int(np.prod(kdims[:-1] or [1]))
    return 2.0 * out_elems * per_out


_INSTR_RE = re.compile(
    r"^(?:ROOT )?%?(?P<n>[^ ]+) = "
    r"(?P<shape>\((?:[^()]|\([^()]*\))*\)|[^ ]+) "
    r"(?P<op>[\w-]+)\((?P<args>[^)]*)\)")


def iter_instructions(hlo_text: str):
    """Yield ``(name, shape, opcode, operands, line)`` for every
    instruction of an HLO text dump — top level or inside fused/nested
    computations. The ONE operand parser (apexlint's tile rule shares
    it): operand names are resolved by the caller against a module-wide
    name→shape table since optimized HLO names operands without inline
    types."""
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _INSTR_RE.match(line)
        if not m:
            continue
        args_text = m.group("args")
        if "%" in args_text:
            # older printers inline operand types ("f32[32,64]{1,0} %x"),
            # whose commas break naive splitting — take the %-prefixed
            # names directly
            operands = re.findall(r"%([^\s,)]+)", args_text)
        else:
            operands = [a.strip().split()[-1]
                        for a in args_text.split(",") if a.strip()]
        yield (m.group("n").lstrip("%"), m.group("shape"),
               m.group("op"), operands, line)


def op_estimates(fn, *args, top: Optional[int] = None,
                 **kwargs) -> List[OpEstimate]:
    """Per-instruction FLOPs/bytes estimates from the optimized HLO.

    Walks every instruction of the compiled module (a module-wide
    name→shape symbol table resolves operand shapes, since optimized HLO
    names operands without inline types); computes matmul FLOPs for
    ``dot`` and ``convolution`` ops wherever they appear — top level or
    inside fused computations — and memory traffic for every op from its
    result shape. Sorted by flops desc, then bytes.
    """
    return op_estimates_from_text(compiled_hlo(fn, *args, **kwargs),
                                  top=top)


def op_estimates_from_text(text: str,
                           top: Optional[int] = None) -> List[OpEstimate]:
    """:func:`op_estimates` over an already-dumped HLO text, for
    callers that hold the module text rather than a traceable fn (the
    flat per-instruction estimate; :mod:`apex_tpu.prof.roofline` walks
    the same text separately because it additionally needs
    per-computation FLOP fold-in and scope/operand metadata)."""
    shapes: Dict[str, str] = {}
    parsed = []
    for name, shape, op, operands, line in iter_instructions(text):
        shapes[name] = shape
        parsed.append((name, shape, op, operands, line))

    out: List[OpEstimate] = []
    for name, shape, opcode, operands, line in parsed:
        if opcode in ("parameter", "constant", "tuple", "get-tuple-element"):
            continue
        out_elems, out_bytes = _shape_elems_bytes(shape)
        if opcode == "dot":
            flops = _dot_flops(line, out_elems, operands, shapes)
        elif opcode == "convolution":
            flops = _conv_flops(line, out_elems, operands, shapes)
        else:
            flops = 0.0
        _, in_bytes = _shape_elems_bytes(
            " ".join(shapes.get(o, "") for o in operands))
        out.append(OpEstimate(name=name, opcode=opcode, flops=flops,
                              bytes=float(out_bytes + in_bytes),
                              hlo=line[:400]))
    out.sort(key=lambda r: (-r.flops, -r.bytes))
    return out[:top] if top else out
