"""apex_tpu.ckpt — elastic, donation-safe, async sharded checkpointing.

The resilience layer (ROADMAP item 5a; see docs/checkpointing.md):
training state survives crash, preemption, and silent-rank hangs, and
resumes on a *different* mesh shape. Four pieces:

- **snapshot** (:mod:`~apex_tpu.ckpt.snapshot`): donation-safe async
  device→host capture of the full training tuple (params/masters, ZeRO
  optimizer shards, AmpState scalers, Metrics, RNG keys) — fresh device
  copies + background D2H, double-buffered, so the step path pays only
  the copy dispatch;
- **format** (:mod:`~apex_tpu.ckpt.format`): one ``npz`` per process +
  a content-hashed manifest, every file temp-then-rename and the
  manifest committed LAST — a crash at any instant of a save leaves the
  previous checkpoint loadable;
- **elastic** (:mod:`~apex_tpu.ckpt.elastic`): restore re-partitions
  ZeRO slot buffers to the target mesh's ``zero_size``
  (gather-by-manifest → truncate/re-pad → re-scatter), bitwise-equal to
  an uninterrupted run on the new mesh;
- **escalate** (:mod:`~apex_tpu.ckpt.escalate`): the
  ``HangWatchdog``/``FlightRecorder`` policy that turns a silent rank
  or a SIGTERM preemption into checkpoint-save → crash-dump → nonzero
  exit, which :func:`apex_tpu.parallel.launch.elastic_run` answers with
  restart-on-a-smaller-mesh.

::

    mgr = ckpt.CheckpointManager("ckpts", event_sink=logger.record_ckpt)
    policy = ckpt.EscalationPolicy(mgr, recorder=recorder)
    wd = trace.HangWatchdog(120, recorder=recorder, on_stall=policy)
"""

from apex_tpu.ckpt.elastic import repartition_flat, zero_layout
from apex_tpu.ckpt.escalate import (ESCALATION_EXIT_CODE,
                                    EscalationPolicy, PreemptionError)
from apex_tpu.ckpt.format import (CheckpointError, checkpoint_in_use,
                                  checkpoint_is_in_use, committed_steps,
                                  gc_checkpoints, latest_checkpoint,
                                  read_manifest, step_dir)
from apex_tpu.ckpt.manager import CheckpointManager
from apex_tpu.ckpt.snapshot import (HostSnapshot, ShardChunks,
                                    Snapshotter, device_snapshot)

__all__ = [
    "CheckpointManager", "Snapshotter", "HostSnapshot", "ShardChunks",
    "device_snapshot",
    "CheckpointError", "latest_checkpoint", "committed_steps",
    "gc_checkpoints", "read_manifest", "step_dir",
    "checkpoint_in_use", "checkpoint_is_in_use",
    "repartition_flat", "zero_layout",
    "EscalationPolicy", "PreemptionError", "ESCALATION_EXIT_CODE",
]
