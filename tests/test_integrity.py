"""The silent-divergence defense: cross-replica integrity fingerprints
(fold / in-graph compare / veto), the quorum vote naming the minority,
the bit-exact in-place repair broadcast, the GuardPolicy integrity rung,
the chaos mantissa-bitflip + replica-targeting sites, and the
``--kind integrity`` event schema (valid stream + negative twins). The
full end-to-end claims — repair bitwise vs a fault-free oracle, the
no-majority coordinated-rewind fall-through, the EF-int8 hierarchical
fingerprint-clean proof — live in ``scripts/integrity_audit.py --cpu8``.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import guard


def _rep(mesh, tree):
    return jax.device_put(tree, NamedSharding(mesh, P()))


def _diverge(leaf, replica, bit=12):
    """One replica's buffer with a mantissa bit of element 0 flipped —
    the sharding still claims replication."""
    orig = np.array(np.asarray(leaf), copy=True)
    bufs = []
    for i, d in enumerate(leaf.sharding.mesh.devices.flat):
        v = np.array(orig, copy=True)
        if i == replica:
            fv = v.reshape(-1)[:1].view(np.uint32)
            fv[0] ^= np.uint32(1 << bit)
        bufs.append(jax.device_put(v, d))
    return jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, bufs)


# --- the fold -----------------------------------------------------------------

class TestFingerprint:
    def test_deterministic_and_bit_sensitive(self):
        x = {"w": jnp.linspace(0.1, 1.0, 64, dtype=jnp.float32),
             "b": jnp.zeros((8,), jnp.float32)}
        a = int(guard.fingerprint_tree(x))
        assert int(guard.fingerprint_tree(x)) == a
        v = np.asarray(x["w"]).copy()
        iv = v[:1].view(np.uint32)
        iv[0] ^= np.uint32(1)           # the least significant mantissa bit
        y = {"w": jnp.asarray(v), "b": x["b"]}
        assert int(guard.fingerprint_tree(y)) != a

    def test_position_sensitive_within_a_leaf(self):
        """The fold weights each element's bits by a per-position odd
        constant: two elements swapping values IS a divergence and
        must change the fingerprint (a plain sum would be blind to
        it), while the wraparound addition itself stays reduction-
        order-independent — safe to compare across replicas
        regardless of per-device scheduling."""
        rng = np.random.RandomState(0)
        v = rng.randn(128).astype(np.float32)
        a = int(guard.fingerprint_tree(jnp.asarray(v)))
        b = int(guard.fingerprint_tree(jnp.asarray(v[::-1].copy())))
        assert a != b

    @pytest.mark.parametrize("bit", [12, 31])
    def test_compensating_flips_detected(self, bit):
        """Same-significance flips in two elements — one GAINS the
        bit, one LOSES it — leave a plain bit-sum unchanged, and for
        the sign bit even a position-WEIGHTED sum cancels exactly
        (2³¹·Δw ≡ 0 mod 2³² for every even weight gap); the per-term
        avalanche must still see the divergence."""
        iv = np.asarray([0x3FC00000 | np.uint32(0 << bit),
                         0x40200000 | np.uint32(1 << bit)], np.uint32)
        v = iv.view(np.float32)
        a = int(guard.fingerprint_tree(jnp.asarray(v)))
        iw = iv.copy()
        iw[0] |= np.uint32(1 << bit)                 # element 0 gains
        iw[1] &= ~np.uint32(1 << bit)                # element 1 loses
        assert int(iw[0]) + int(iw[1]) == int(iv[0]) + int(iv[1]), \
            "fixture must be sum-neutral (what a linear fold misses)"
        b = int(guard.fingerprint_tree(jnp.asarray(iw.view(np.float32))))
        assert b != a

    def test_leaf_position_sensitive(self):
        """Swapping two equal-shaped leaves must change the fold (a
        swap is a real divergence)."""
        x = jnp.linspace(0.0, 1.0, 16, dtype=jnp.float32)
        y = jnp.linspace(2.0, 3.0, 16, dtype=jnp.float32)
        assert (int(guard.fingerprint_tree({"a": x, "b": y}))
                != int(guard.fingerprint_tree({"a": y, "b": x})))

    def test_cross_leaf_element_exchange_detected(self):
        """The seed identity must be injective ACROSS leaves: with
        per-leaf arithmetic-progression seeds, (leaf i, pos k+2) and
        (leaf i+1, pos k) aliased and an exact two-element exchange
        at the aliased offsets cancelled — the global-lane-offset
        identity must see every such transposition."""
        rng = np.random.RandomState(3)
        a = rng.randn(8).astype(np.float32)
        b = rng.randn(8).astype(np.float32)
        clean = int(guard.fingerprint_tree(
            {"a": jnp.asarray(a), "b": jnp.asarray(b)}))
        for ka in range(8):          # every cross-leaf offset pair of
            for kb in range(0, 8, 3):  # the old aliasing shape + more
                a2, b2 = a.copy(), b.copy()
                a2[ka], b2[kb] = b[kb], a[ka]
                swapped = int(guard.fingerprint_tree(
                    {"a": jnp.asarray(a2), "b": jnp.asarray(b2)}))
                assert swapped != clean, (ka, kb)

    def test_uint_view_dtype_is_the_shared_table(self):
        """The fold and the repair broadcast must agree on bit-exact
        coverage — both read apex_tpu.utils.uint_view_dtype."""
        from apex_tpu.utils import uint_view_dtype
        assert uint_view_dtype(jnp.float32) == jnp.uint32
        assert uint_view_dtype(jnp.bfloat16) == jnp.uint16
        assert uint_view_dtype(jnp.float16) == jnp.uint16
        assert uint_view_dtype(jnp.float64) == jnp.uint32  # lane pair

    def test_mixed_dtypes_fold(self):
        tree = {"f32": jnp.ones((4,), jnp.float32),
                "bf16": jnp.ones((4,), jnp.bfloat16),
                "i32": jnp.arange(4, dtype=jnp.int32),
                "bool": jnp.asarray([True, False]),
                "empty": jnp.zeros((0,), jnp.float32)}
        fp = guard.fingerprint_tree(tree)
        assert fp.dtype == jnp.uint32

    def test_uncovered_dtype_refused_loudly(self, mesh8):
        """A dtype the fold cannot cover bit-exactly must raise, not
        silently skip — a skipped leaf would be an undetectable (and
        unrepairable) hole in the guarantee."""
        bad = {"c": jnp.ones((4,), jnp.complex64)}
        with pytest.raises(TypeError):
            guard.fingerprint_tree(bad)
        from apex_tpu.parallel import replica_broadcast
        with pytest.raises(TypeError):
            jax.jit(jax.shard_map(
                lambda t: replica_broadcast(t, "data", source=0),
                mesh=mesh8, in_specs=(P(),), out_specs=P(),
                check_vma=False))(_rep(mesh8, bad))

    def test_init_validation(self):
        with pytest.raises(ValueError):
            guard.integrity_init(guard.IntegrityConfig(check_every=0),
                                 world=8)
        with pytest.raises(ValueError):
            guard.integrity_init(world=1)


# --- the in-graph check -------------------------------------------------------

def _check_step(icfg, mesh):
    def f(p, ist):
        return guard.integrity_check(ist, icfg, p, axis_name="data")
    return jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))


class TestIntegrityCheck:
    def test_cadence_skips_off_steps(self, mesh8):
        icfg = guard.IntegrityConfig(check_every=3)
        ist = guard.integrity_init(icfg, world=8)
        p = _rep(mesh8, {"w": jnp.ones((16,), jnp.float32)})
        step = _check_step(icfg, mesh8)
        for s in range(6):
            ist = step(p, ist)
        assert int(ist.step) == 6
        assert int(ist.check_count) == 2          # steps 0 and 3
        assert int(ist.mismatch_count) == 0
        assert int(ist.last_check_step) == 3

    def test_divergence_detected_and_minority_gathered(self, mesh8):
        icfg = guard.IntegrityConfig(check_every=1)
        ist = guard.integrity_init(icfg, world=8)
        p = _rep(mesh8, {"w": jnp.linspace(0.1, 1.0, 32,
                                           dtype=jnp.float32)})
        step = _check_step(icfg, mesh8)
        ist = step(p, ist)
        assert not bool(ist.divergent)
        p = {"w": _diverge(p["w"], replica=5)}
        ist = step(p, ist)
        assert bool(ist.divergent)
        assert int(ist.mismatch_count) == 1
        fps = np.asarray(ist.rank_fps)
        bad = [i for i in range(8) if fps[i] != fps[0]]
        assert bad == [5]

    def test_divergent_flag_clears_on_off_step(self, mesh8):
        icfg = guard.IntegrityConfig(check_every=2)
        ist = guard.integrity_init(icfg, world=8)
        p = {"w": _diverge(_rep(mesh8, jnp.ones((8,), jnp.float32)),
                           replica=1)}
        step = _check_step(icfg, mesh8)
        ist = step({"w": p["w"]}, ist)            # step 0: check, diverged
        assert bool(ist.divergent)
        ist = step({"w": p["w"]}, ist)            # step 1: off-step
        assert not bool(ist.divergent)            # transient cleared
        assert int(ist.mismatch_count) == 1       # cumulative kept

    def test_resize_for_elastic_resume(self):
        """A checkpointed IntegrityState restored onto a different
        mesh size: counters (history) survive, the per-replica vector
        and last-check transients re-init for the new electorate;
        same-world passes through untouched."""
        icfg = guard.IntegrityConfig(check_every=1)
        ist = guard.integrity_init(icfg, world=8)._replace(
            mismatch_count=jnp.int32(3), check_count=jnp.int32(7),
            step=jnp.int32(7), divergent=jnp.bool_(True),
            rank_fps=jnp.arange(8, dtype=jnp.uint32))
        small = guard.integrity_resize(ist, world=4)
        assert small.rank_fps.shape == (4,)
        assert int(small.mismatch_count) == 3    # history preserved
        assert int(small.check_count) == 7
        assert not bool(small.divergent)
        assert guard.integrity_resize(ist, world=8) is ist
        with pytest.raises(ValueError):
            guard.integrity_resize(ist, world=1)
        # a fresh policy's first poll over the resized state: healed
        # forensic note with the no-check-yet sentinel NULLED, and the
        # event validates under the integrity schema
        from apex_tpu.guard.policy import GuardPolicy
        from scripts.check_metrics_schema import check_integrity_lines
        iev = []
        pol = GuardPolicy(integrity_sink=iev.append)
        assert pol.update_integrity(0, small).kind == "none"
        assert len(iev) == 1 and iev[0]["healed"] is True
        assert iev[0]["check_step"] is None
        assert check_integrity_lines([json.dumps(iev[0])]) == []

    def test_replica_ok_feeds_guard_veto(self, mesh8):
        """guard_observe(replica_ok=False) raises the skip-class
        divergence anomaly: the commit is vetoed, the counter moves,
        and the polluted loss never enters the window."""
        cfg = guard.GuardConfig(window=8, min_history=2)
        gs = guard.guard_init(cfg)
        for i in range(4):
            gs = guard.guard_observe(gs, cfg, loss=jnp.float32(1.0),
                                     replica_ok=True)
        count_before = int(gs.count)
        gs = guard.guard_observe(gs, cfg, loss=jnp.float32(1.0),
                                 replica_ok=False)
        assert int(gs.anomaly) == guard.A_REPLICA_DIVERGENCE
        assert int(gs.replica_divergence_count) == 1
        assert int(gs.skip_count) == 1
        assert int(gs.count) == count_before      # window not polluted
        assert not bool(guard.guard_ok(gs))
        new = {"w": jnp.ones((2,), jnp.float32)}
        old = {"w": jnp.zeros((2,), jnp.float32)}
        kept = guard.guard_commit(gs, new, old, cfg)
        np.testing.assert_array_equal(np.asarray(kept["w"]),
                                      np.asarray(old["w"]))
        # divergence must NOT back the LR off (not an instability)
        assert float(gs.lr_scale) == 1.0


# --- the vote -----------------------------------------------------------------

class TestVote:
    def test_single_bad_replica(self):
        v = guard.vote([7, 7, 9, 7, 7, 7, 7, 7])
        assert v.has_majority and v.minority == (2,)
        assert v.source_rank == 0 and v.n_ranks == 8

    def test_source_is_lowest_majority_rank(self):
        v = guard.vote([3, 7, 7, 7])
        assert v.minority == (0,) and v.source_rank == 1

    def test_two_of_two_tie_has_no_majority(self):
        v = guard.vote([1, 2])
        assert not v.has_majority
        assert v.source_rank is None and v.minority == ()

    def test_all_disagree_has_no_majority(self):
        assert not guard.vote([1, 2, 3, 4]).has_majority

    def test_exact_half_is_not_a_majority(self):
        assert not guard.vote([5, 5, 6, 6]).has_majority
        assert guard.vote([5, 5, 5, 6]).has_majority


# --- the repair broadcast -----------------------------------------------------

class TestRepair:
    def test_repair_is_bit_exact_on_every_buffer(self, mesh8):
        tree = _rep(mesh8, {
            "w": jnp.asarray([-0.0, 1.5, -2.25, 0.0], jnp.float32),
            "h": jnp.asarray([1.0, -0.5], jnp.bfloat16),
            "n": jnp.arange(4, dtype=jnp.int32)})
        orig = {k: np.array(np.asarray(v), copy=True)
                for k, v in tree.items()}
        tree = dict(tree, w=_diverge(tree["w"], replica=3))
        repair = guard.make_repair_fn(mesh8, "data")
        verify = guard.make_verify_fn(mesh8, "data")
        mn, mx, _ = verify(tree)
        assert int(mn) != int(mx)
        fixed = repair(tree, jnp.int32(0))
        mn, mx, _ = verify(fixed)
        assert int(mn) == int(mx)
        for k in orig:
            for sh in fixed[k].addressable_shards:
                got = np.asarray(sh.data)
                assert got.dtype == orig[k].dtype
                np.testing.assert_array_equal(got, orig[k])
        # -0.0 sign survived the broadcast (bit-pattern psum; a float
        # psum would have collapsed it to +0.0 and failed re-verify)
        assert np.signbit(np.asarray(fixed["w"])[0])

    def test_repair_from_nonzero_source(self, mesh8):
        leaf = _rep(mesh8, jnp.linspace(0.0, 1.0, 8, jnp.float32))
        bad = _diverge(leaf, replica=0)           # replica 0 is the bad one
        repair = guard.make_repair_fn(mesh8, "data")
        fixed = repair({"w": bad}, jnp.int32(4))
        want = np.asarray(leaf)
        for sh in fixed["w"].addressable_shards:
            np.testing.assert_array_equal(np.asarray(sh.data), want)


# --- the policy rung ----------------------------------------------------------

def _policy_with_sinks(**kw):
    iev, gev = [], []
    pol = guard.GuardPolicy(integrity_sink=iev.append,
                            event_sink=gev.append, **kw)
    return pol, iev, gev


class TestPolicyIntegrity:
    def _diverged_ist(self, mesh8, replica=2):
        icfg = guard.IntegrityConfig(check_every=1)
        ist = guard.integrity_init(icfg, world=8)
        p = {"w": _diverge(
            _rep(mesh8, jnp.linspace(0.1, 1.0, 16, jnp.float32)),
            replica=replica)}
        return _check_step(icfg, mesh8)(p, ist), p

    def test_clean_state_no_events(self, mesh8):
        icfg = guard.IntegrityConfig(check_every=1)
        ist = guard.integrity_init(icfg, world=8)
        p = _rep(mesh8, {"w": jnp.ones((8,), jnp.float32)})
        ist = _check_step(icfg, mesh8)(p, ist)
        pol, iev, _ = _policy_with_sinks()
        assert pol.update_integrity(0, ist).kind == "none"
        assert iev == []

    def test_mismatch_votes_repair_and_repairs(self, mesh8):
        ist, p = self._diverged_ist(mesh8)
        pol, iev, _ = _policy_with_sinks()
        act = pol.update_integrity(0, ist)
        assert act.kind == "repair"
        assert act.classes == ("replica_divergence",)
        assert pol.last_vote.minority == (2,)
        kinds = [e["kind"] for e in iev]
        assert kinds == ["integrity_check", "integrity_vote"]
        assert iev[1]["action"] == "repair"
        assert iev[1]["minority"] == [2]
        fixed, ok = pol.repair(
            0, p, repair_fn=guard.make_repair_fn(mesh8, "data"),
            verify_fn=guard.make_verify_fn(mesh8, "data"))
        assert ok and pol.repairs_done == 1 and pol.rewinds_done == 0
        assert iev[-1]["kind"] == "integrity_repair"
        assert iev[-1]["verified"] is True

    def test_coarse_poll_recovers_missed_mismatch(self, mesh8):
        ist, _p = self._diverged_ist(mesh8)
        pol, iev, _ = _policy_with_sinks(poll_every=4)
        assert pol.update_integrity(1, ist).kind == "none"  # off-poll
        act = pol.update_integrity(5, ist)    # cumulative delta seen
        assert act.kind == "repair"

    def test_no_majority_with_exhausted_budget_escalates(self, mesh8):
        """The integrity rung honors the same rewind_budget terminal
        as the guard ladder: a deterministic no-majority fault must
        not loop restore→re-diverge forever."""
        icfg = guard.IntegrityConfig(check_every=1)
        ist = guard.integrity_init(icfg, world=8)
        # every replica diverged differently: no majority
        leaf = _rep(mesh8, jnp.linspace(0.1, 1.0, 16, jnp.float32))
        bufs = []
        orig = np.array(np.asarray(leaf), copy=True)
        for i, d in enumerate(mesh8.devices.flat):
            v = np.array(orig, copy=True)
            fv = v.reshape(-1)[:1].view(np.uint32)
            fv[0] ^= np.uint32(1 << (5 + i))
            bufs.append(jax.device_put(v, d))
        p = {"w": jax.make_array_from_single_device_arrays(
            leaf.shape, leaf.sharding, bufs)}
        ist = _check_step(icfg, mesh8)(p, ist)
        pol, iev, _ = _policy_with_sinks(rewind_budget=2)
        assert pol.update_integrity(0, ist).kind == "rewind"
        pol2, iev2, _ = _policy_with_sinks(rewind_budget=2)
        pol2.rewinds_done = 2                    # budget spent
        act = pol2.update_integrity(0, ist)
        assert act.kind == "escalate"
        votes = [e for e in iev2 if e["kind"] == "integrity_vote"]
        assert votes and votes[0]["action"] == "escalate"

    def test_observe_only_reports_never_acts(self, mesh8):
        ist, _p = self._diverged_ist(mesh8)
        pol, iev, _ = _policy_with_sinks(observe_only=True)
        act = pol.update_integrity(0, ist)
        assert act.kind == "none"
        assert [e["action"] for e in iev
                if e["kind"] == "integrity_vote"] == ["observe"]

    def test_repair_without_vote_raises(self):
        pol, _, _ = _policy_with_sinks()
        with pytest.raises(ValueError):
            pol.repair(0, {}, repair_fn=None, verify_fn=None)

    def test_stale_vote_cannot_drive_a_second_repair(self, mesh8):
        """One vote arms at most one repair: a retry without a fresh
        update_integrity verdict must refuse — a stale source choice
        from a previous incident must never drive a broadcast."""
        ist, p = self._diverged_ist(mesh8)
        pol, _, _ = _policy_with_sinks()
        assert pol.update_integrity(0, ist).kind == "repair"
        rf = guard.make_repair_fn(mesh8, "data")
        vf = guard.make_verify_fn(mesh8, "data")
        _fixed, ok = pol.repair(0, p, repair_fn=rf, verify_fn=vf)
        assert ok
        assert pol.last_vote is not None      # kept for forensics
        with pytest.raises(ValueError):
            pol.repair(1, p, repair_fn=rf, verify_fn=vf)

    def test_absorb_verify_prevents_stale_vote_replay(self, mesh8):
        """A checkpoint taken on the repair step must not freeze the
        detection-time disagreement: after repair + absorb_verify, a
        FRESH policy (simulated restart, zero baseline) sees the
        nonzero cumulative counter but AGREEING rank_fps — healed
        branch, no verdict, no spurious repair."""
        ist, p = self._diverged_ist(mesh8)
        pol, _, _ = _policy_with_sinks()
        assert pol.update_integrity(0, ist).kind == "repair"
        fixed, ok = pol.repair(
            0, p, repair_fn=guard.make_repair_fn(mesh8, "data"),
            verify_fn=guard.make_verify_fn(mesh8, "data"))
        assert ok
        ist = guard.absorb_verify(ist, *pol.last_verify)
        assert not bool(ist.divergent)
        fps = np.asarray(ist.rank_fps)
        assert (fps == fps[0]).all()
        assert int(ist.mismatch_count) == 1      # history preserved
        fresh, iev2, _ = _policy_with_sinks()
        act = fresh.update_integrity(0, ist)
        assert act.kind == "none"
        assert [e["kind"] for e in iev2] == ["integrity_check"]
        assert iev2[0]["healed"] is True

    def test_restored_counter_with_healed_replicas_stays_quiet(self):
        """A fresh policy's first poll over a RESTORED IntegrityState
        whose cumulative mismatch_count predates the restart: the
        gathered fingerprints all agree (the divergence was repaired
        before the checkpoint), so no verdict and no phantom events —
        just a baseline resync."""
        icfg = guard.IntegrityConfig(check_every=1)
        ist = guard.integrity_init(icfg, world=8)._replace(
            mismatch_count=jnp.int32(2), check_count=jnp.int32(5),
            step=jnp.int32(5), last_check_step=jnp.int32(4))
        pol, iev, _ = _policy_with_sinks()
        assert pol.update_integrity(0, ist).kind == "none"
        # the DETECTION stays on the forensic record (flagged healed,
        # no vote, no repair) — but no phantom verdict
        assert [e["kind"] for e in iev] == ["integrity_check"]
        assert iev[0]["healed"] is True
        assert pol.last_vote is None
        # and the baseline is synced: the next poll is fully quiet
        assert pol.update_integrity(1, ist).kind == "none"
        assert len(iev) == 1

    def test_generation_fences_events(self, mesh8):
        ist, _p = self._diverged_ist(mesh8)
        pol, iev, _ = _policy_with_sinks(generation=lambda: 7)
        pol.update_integrity(0, ist)
        assert all(e["generation"] == 7 for e in iev)

    def test_unfenced_events_carry_null_generation(self, mesh8):
        ist, _p = self._diverged_ist(mesh8)
        pol, iev, _ = _policy_with_sinks()
        pol.update_integrity(0, ist)
        assert all(e["generation"] is None for e in iev)

    def test_guard_update_names_the_class(self):
        """The GuardState counter half: update() reports the
        divergence skip as a guard_anomaly with the new class."""
        cfg = guard.GuardConfig(window=8, min_history=2)
        gs = guard.guard_init(cfg)
        gs = guard.guard_observe(gs, cfg, loss=jnp.float32(1.0),
                                 replica_ok=False)
        pol, _, gev = _policy_with_sinks()
        act = pol.update(0, gs)
        assert act.kind == "skip"
        assert "replica_divergence" in act.classes
        anom = [e for e in gev if e["kind"] == "guard_anomaly"]
        assert anom and anom[0]["classes"] == ["replica_divergence"]


# --- chaos: the silent-fault injector -----------------------------------------

class TestChaosMantissa:
    def test_plan_accepts_the_new_kind(self):
        plan = guard.FaultPlan().add(3, "params", "bitflip_mantissa",
                                     arg=12)
        f = plan.at(3, 0, "params")
        assert f.kind == "bitflip_mantissa"
        rt = guard.FaultPlan.from_json(plan.to_json())
        assert rt == plan

    def test_mantissa_flip_is_always_finite(self):
        """Any arg — including ones that would index exponent/sign
        bits — lands on a mantissa bit, so the corrupted value is
        finite by construction (the whole point: silent to the
        nonfinite probe)."""
        for arg in (0, 12, 22, 23, 30, 31, 100):
            state = {"w": jnp.asarray([1.5, 2.0], jnp.float32)}
            f = guard.Fault(0, "params", "bitflip_mantissa", 0,
                            float(arg))
            out = guard.ChaosHarness._corrupt_params(state, f)
            v = np.asarray(out["w"])
            assert np.all(np.isfinite(v)), arg
            assert v[0] != 1.5, arg              # but it DID corrupt

    def test_legacy_bitflip_still_flips_the_exponent(self):
        """The default bitflip stays LOUD (top exponent bit → a huge
        or non-finite value the existing probes catch) — the mantissa
        mode exists precisely because this one is not silent."""
        state = {"w": jnp.asarray([1.5], jnp.float32)}
        f = guard.Fault(0, "params", "bitflip", 0, 0.0)
        out = guard.ChaosHarness._corrupt_params(state, f)
        v = float(np.asarray(out["w"])[0])
        assert not np.isfinite(v) or abs(v) > 1e30

    def test_replica_targeting_diverges_one_buffer(self, mesh8):
        state = _rep(mesh8, {"w": jnp.linspace(0.1, 1.0, 8,
                                               jnp.float32)})
        orig = np.array(np.asarray(state["w"]), copy=True)
        plan = guard.FaultPlan().add(0, "params", "bitflip_mantissa",
                                     arg=5)
        h = guard.ChaosHarness(plan, replica=6)
        out = h.post_step(0, state)
        shards = list(out["w"].addressable_shards)
        same = [i for i, sh in enumerate(shards)
                if np.array_equal(np.asarray(sh.data), orig)]
        assert len(same) == 7 and 6 not in same
        # the logical (device-0) view still reads clean — the lie a
        # silent fault tells every host-side consumer
        np.testing.assert_array_equal(np.asarray(out["w"]), orig)

    def test_replica_out_of_range_refused(self, mesh8):
        state = _rep(mesh8, {"w": jnp.ones((4,), jnp.float32)})
        plan = guard.FaultPlan().add(0, "params", "bitflip_mantissa")
        h = guard.ChaosHarness(plan, replica=11)
        with pytest.raises(ValueError):
            h.post_step(0, state)

    def test_sharded_leaf_refused(self, mesh8):
        """replica= promises a dp replica index — on a sharded leaf a
        flat device index is neither a replica nor shape-compatible;
        the harness must refuse loudly instead of corrupting the
        wrong shard."""
        state = {"w": jax.device_put(
            jnp.ones((16,), jnp.float32),
            NamedSharding(mesh8, P("data")))}
        plan = guard.FaultPlan().add(0, "params", "bitflip_mantissa")
        h = guard.ChaosHarness(plan, replica=2)
        with pytest.raises(ValueError):
            h.post_step(0, state)


# --- event schema -------------------------------------------------------------

def _lines(events):
    return [json.dumps(e) for e in events]


_CHECK_EV = {"kind": "integrity_check", "rank": 0, "step": 4,
             "check_step": 4, "n_ranks": 8, "mismatch_count": 1,
             "new_mismatches": 1, "fp_min": 100, "fp_max": 200,
             "generation": None, "wall_time": 1.0}
_VOTE_EV = {"kind": "integrity_vote", "rank": 0, "step": 4,
            "action": "repair", "n_ranks": 8, "minority": [1],
            "source_rank": 0, "majority_fp": 100, "generation": None,
            "reason": "minority [1] diverged", "wall_time": 1.0}
_REPAIR_EV = {"kind": "integrity_repair", "rank": 0, "step": 4,
              "action": "repair", "source_rank": 0, "minority": [1],
              "verified": True, "generation": None, "reason": None,
              "wall_time": 1.0}


class TestIntegritySchema:
    def _check(self, lines):
        from scripts.check_metrics_schema import check_integrity_lines
        return check_integrity_lines(lines)

    def test_valid_stream(self):
        assert self._check(_lines([_CHECK_EV, _VOTE_EV,
                                   _REPAIR_EV])) == []

    def test_no_majority_vote_nullable_source(self):
        ev = dict(_VOTE_EV, action="rewind", source_rank=None,
                  majority_fp=None, minority=[])
        assert self._check(_lines([ev])) == []

    def test_unknown_kind_rejected(self):
        errs = self._check(_lines([dict(_CHECK_EV,
                                        kind="integrity_meow")]))
        assert errs and "kind" in errs[0]

    def test_missing_required_key_rejected(self):
        ev = dict(_VOTE_EV)
        del ev["minority"]
        assert any("minority" in e for e in self._check(_lines([ev])))

    def test_bad_action_rejected(self):
        assert self._check(_lines([dict(_VOTE_EV, action="reboot")]))
        assert self._check(_lines([dict(_REPAIR_EV, action="rewind",
                                        verified=False)]))

    def test_negative_minority_rank_rejected(self):
        assert self._check(_lines([dict(_VOTE_EV, minority=[-1])]))

    def test_nonbool_verified_rejected(self):
        assert self._check(_lines([dict(_REPAIR_EV, verified=1)]))

    def test_action_verified_contradiction_rejected(self):
        assert self._check(_lines([dict(_REPAIR_EV, action="repair",
                                        verified=False)]))

    def test_null_step_rejected(self):
        assert self._check(_lines([dict(_CHECK_EV, step=None)]))

    def test_healed_flag_validates(self):
        assert self._check(_lines([dict(_CHECK_EV, healed=True)])) == []
        assert self._check(_lines([dict(_CHECK_EV, healed="yes")]))

    def test_post_resize_null_check_step_validates(self):
        """The elastic-resume sentinel: a healed first poll after
        integrity_resize has no check under THIS electorate —
        check_step must be null on the wire, and the validator must
        accept exactly that shape (the library's own emission)."""
        ev = dict(_CHECK_EV, check_step=None, healed=True)
        assert self._check(_lines([ev])) == []
        assert self._check(_lines([dict(_CHECK_EV, check_step=-1)]))

    def test_guard_classes_enum_grew(self):
        from scripts.check_metrics_schema import GUARD_CLASSES
        assert "replica_divergence" in GUARD_CLASSES

    def test_logger_channel_round_trip(self, tmp_path):
        from apex_tpu import monitor
        out = tmp_path / "integrity.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], integrity_sink=monitor.JSONLSink(str(out)))
        logger.record_integrity(dict(_VOTE_EV))
        logger.close()
        with open(out) as f:
            assert self._check(f) == []


# --- the amp hook -------------------------------------------------------------

class TestAmpIntegration:
    def test_amp_step_threads_replica_ok(self):
        """``amp_opt.step(guard=(gs, cfg, replica_ok))`` — the 3-tuple
        feeds the integrity verdict into amp's unified observe+commit:
        replica_ok=False vetoes the commit and counts the class; the
        legacy 2-tuple stays untouched."""
        import optax
        from apex_tpu import amp
        params = {"w": jnp.ones((4, 2), jnp.float32)}
        cfg = guard.GuardConfig(window=8, min_history=2)
        amp_opt, state = amp.initialize(params, optax.sgd(0.1), "O2",
                                        half_dtype=jnp.bfloat16)

        def lf(mp):
            return jnp.mean(jnp.square(mp["w"]))

        gs = guard.guard_init(cfg)
        s2, _loss, committed, gs = amp_opt.step(
            state, lf, guard=(gs, cfg, jnp.bool_(False)))
        assert not bool(committed)
        assert int(gs.replica_divergence_count) == 1
        np.testing.assert_array_equal(np.asarray(s2.params["w"]),
                                      np.asarray(state.params["w"]))
        s3, _loss, committed, gs = amp_opt.step(
            state, lf, guard=(gs, cfg))          # legacy 2-tuple
        assert bool(committed)
        assert int(gs.replica_divergence_count) == 1
        assert not np.array_equal(np.asarray(s3.params["w"]),
                                  np.asarray(state.params["w"]))


# --- the compile-check case ---------------------------------------------------

class TestCompileCheck:
    def test_integrity_case_runs_green(self):
        from apex_tpu.ops import compile_check as cc
        assert cc.run(pattern="integrity")
