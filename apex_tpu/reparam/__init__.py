"""apex_tpu.reparam — weight reparameterization (apex.reparameterization).

Reference: `apex/reparameterization/__init__.py` exports
``apply_weight_norm`` / ``remove_weight_norm`` and the ``WeightNorm``
reparameterization class.
"""

from apex_tpu.reparam.weight_norm import (WeightNorm, apply_weight_norm,
                                          remove_weight_norm)

__all__ = ["WeightNorm", "apply_weight_norm", "remove_weight_norm"]
