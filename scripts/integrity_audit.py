#!/usr/bin/env python
"""Integrity audit: silently diverge a replica, prove detect→repair.

The asserting sibling of ``chaos_audit.py`` for the silent-divergence
axis (``run_tier1.sh --smoke`` runs it; exit status is the verdict). A
small model trains over the real :mod:`apex_tpu.data.pipeline`
ImageFolder stream, data-parallel over a CPU mesh, with the
:mod:`apex_tpu.guard.integrity` fingerprints riding the jitted step.
Five claims, each printed and asserted:

(a) **zero false positives** — a fault-free fingerprinted run logs
    ZERO integrity events (and zero guard events), every check agrees
    (``mismatch_count == 0``), and driving the step under the host
    policy leaves its compiled HLO BIT-IDENTICAL with no host ops (the
    ``integrity/no-extra-dispatch`` compile-check case pins the
    donated/undonated halves);
(b) **silent corruption is caught and repaired in place** — a seeded
    FINITE mantissa bit-flip on replica 1's device buffer (chaos
    ``params:bitflip_mantissa`` — invisible to the NaN/spike/nonfinite
    detectors by construction) is detected within ``check_every``
    steps by the cross-replica fingerprint compare, the polluted step
    is vetoed in-graph, the quorum vote names replica 1 as the
    minority, and the repair re-broadcasts the majority's exact bits
    with NO checkpoint rewind and the data cursor untouched — after
    which every post-repair loss and the final params are
    **bitwise-equal** to a fault-free oracle;
(c) **no majority ⇒ coordinated rewind** — both replicas of a dp=2
    mesh diverge (differently): the vote finds no strict majority
    (there is no trustworthy broadcast source), and the incident falls
    through to the :class:`~apex_tpu.cluster.RecoveryCoordinator` path
    — one generation bump, rewind to the agreed good step, post-rewind
    losses + final params bitwise vs the oracle;
(d) **the EF-int8 hierarchical sync runs fingerprint-clean** — the
    collectives-v2 runtime proof: a trajectory over the factored
    2-slice × 4-chip mesh with every gradient crossing both hops as
    error-fed int8 keeps params AND post-sync grads bitwise identical
    on all 8 replicas at every step (``mismatch_count == 0``), while
    still converging;
(e) **the event stream validates** — every integrity event passes
    ``check_metrics_schema.py --kind integrity`` and the expected
    kinds are present (guard streams stay valid too).

Usage: python scripts/integrity_audit.py --cpu8
       python scripts/integrity_audit.py        # same audit, local devices
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_STEPS = 12
SAVE_EVERY = 2
CHECK_EVERY = 2
BATCH = 8
IMG = 16          # decode size: D = 16*16*3 = 768 features
# stable for the 768-feature linear-MSE probe model (see chaos_audit)
LR = 0.002
SEED = 3


def _make_cfg():
    from apex_tpu import guard
    return (guard.GuardConfig(window=16, min_history=4, z_threshold=8.0,
                              grad_factor=50.0, lr_growth_interval=3),
            guard.IntegrityConfig(check_every=CHECK_EVERY))


def _make_step(cfg, icfg, mesh, axis):
    """The fingerprint-instrumented DDP step over ``mesh``: integrity
    check on the committed params → grads → registered sync/pmean →
    guard observe (fed the integrity verdict) → guarded commit."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu import guard, parallel
    from apex_tpu.trace.spans import span

    def train_step(params, gs, ist, x, y):
        ist = guard.integrity_check(ist, icfg, params, axis_name=axis)

        def loss_fn(p):
            h = x.reshape(x.shape[0], -1) @ p["w"] + p["b"]
            onehot = jax.nn.one_hot(y, p["b"].shape[0],
                                    dtype=jnp.float32)
            return jnp.mean(jnp.square(h - onehot))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        with span("ddp/sync_gradients", kind="collective"):
            grads = parallel.sync_gradients(grads, axis)
        with span("ddp/loss_pmean", kind="collective"):
            loss = jax.lax.pmean(loss, axis)
        gs = guard.guard_observe(gs, cfg, loss=loss, grads=grads,
                                 params=params,
                                 replica_ok=guard.integrity_ok(ist))
        new_p = jax.tree_util.tree_map(
            lambda p, g: p - LR * gs.lr_scale * g, params, grads)
        return guard.guard_commit(gs, new_p, params, cfg), gs, ist, loss

    return jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), P(axis), P(axis)),
        out_specs=(P(), P(), P(), P()), check_vma=False))


def _init_params(mesh):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.RandomState(0)
    rep = NamedSharding(mesh, P())
    return {
        "w": jax.device_put(jnp.asarray(
            rng.randn(IMG * IMG * 3, 4).astype("float32") * 0.05), rep),
        "b": jax.device_put(jnp.zeros((4,), jnp.float32), rep),
    }


def _diverge_both(params, mesh):
    """Claim (c)'s fault: BOTH replicas' buffers flip a (different)
    mantissa bit — 2 of 2 dp groups diverged, no majority exists."""
    import jax
    import numpy as np

    leaf = params["w"]
    orig = np.array(np.asarray(leaf), copy=True)
    bufs = []
    for i, d in enumerate(mesh.devices.flat):
        v = np.array(orig, copy=True)
        fv = v.reshape(-1)[:1].view(np.uint32)
        fv[0] ^= np.uint32(1 << (10 + i))
        assert np.isfinite(v.reshape(-1)[0])
        bufs.append(jax.device_put(v, d))
    params = dict(params)
    params["w"] = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, bufs)
    return params


def run_guarded(imgroot, workdir, jstep, cfg, icfg, mesh, axis, *,
                plan=None, replica=None, diverge_both_at=None,
                oracle_skip=None, tag="run", n_steps=N_STEPS,
                coordinator_dir=None):
    """One fingerprinted guarded run. ``plan``+``replica`` inject
    replica-targeted chaos; ``diverge_both_at`` applies claim (c)'s
    two-replica fault after that step commits; ``oracle_skip=(at, n)``
    fast-forwards the cursor for the fault-free oracle.

    The checkpoint save runs AFTER the policy polls — a step whose
    integrity check failed must never commit a checkpoint (a silently
    corrupted snapshot would pass every finite-param probe on restore
    and resurrect the fault; docs/resilience.md#integrity)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import ckpt, guard, monitor
    from apex_tpu.data.pipeline import ImageFolderSource

    world = 1
    for a in ((axis,) if isinstance(axis, str) else axis):
        world *= mesh.shape[a]
    shd = NamedSharding(mesh, P(axis))
    events_path = os.path.join(workdir, f"guard_{tag}.jsonl")
    ievents_path = os.path.join(workdir, f"integrity_{tag}.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], guard_sink=monitor.JSONLSink(events_path),
        integrity_sink=monitor.JSONLSink(ievents_path))
    mgr = ckpt.CheckpointManager(os.path.join(workdir, f"ck_{tag}"),
                                 keep=4)
    policy = guard.GuardPolicy(manager=mgr,
                               event_sink=logger.record_guard,
                               integrity_sink=logger.record_integrity,
                               rewind_budget=2)
    coord = member = None
    if coordinator_dir is not None:
        from apex_tpu import cluster
        member = cluster.ClusterMembership(coordinator_dir, rank=0)
        member.join()
        coord = cluster.RecoveryCoordinator(member,
                                            barrier_timeout_s=10.0)
    src = ImageFolderSource(imgroot, batch=BATCH, size=IMG, seed=SEED,
                            workers=4, process_index=0, process_count=1)
    harness = (guard.ChaosHarness(plan, replica=replica)
               if plan is not None else None)
    repair_fn = guard.make_repair_fn(mesh, axis)
    verify_fn = guard.make_verify_fn(mesh, axis)
    params = _init_params(mesh)
    gs = guard.guard_init(cfg)
    ist = guard.integrity_init(icfg, world=world)
    it_box = [None]

    def pull():
        while True:
            if it_box[0] is None:
                it_box[0] = src.epoch()
            try:
                return next(it_box[0])
            except StopIteration:
                it_box[0] = None

    losses, repaired_at, rewound_at = [], [], []
    for step in range(n_steps):
        if oracle_skip and src.cursor_index() == oracle_skip[0]:
            src.skip_batches(oracle_skip[1])
            it_box[0] = None
        x, y = pull()
        xd = jax.device_put(x, shd)
        yd = jax.device_put(np.asarray(y, np.int32), shd)
        params, gs, ist, loss = jstep(params, gs, ist, xd, yd)
        losses.append(np.float32(np.asarray(loss)))
        if harness is not None:
            params = harness.post_step(step, params,
                                       ckpt_root=mgr.root)
        if diverge_both_at is not None and step == diverge_both_at:
            params = _diverge_both(params, mesh)
        policy.update(step, gs)       # guard ladder (anomaly events)
        iact = policy.update_integrity(step, ist)
        rewound = False
        if iact.kind == "repair":
            params, ok = policy.repair(step, params,
                                       repair_fn=repair_fn,
                                       verify_fn=verify_fn,
                                       reason=iact.reason)
            assert ok, "repair re-verification failed"
            # a checkpoint taken THIS step must record the post-repair
            # agreement, not the detection-time disagreement (a
            # restart would otherwise replay the stale vote)
            ist = guard.absorb_verify(ist, *policy.last_verify)
            repaired_at.append(step)
        elif iact.kind == "rewind":
            like = {"params": params, "gs": gs, "ist": ist}
            if coord is not None:
                dec, restored_pair = coord.run_round(
                    policy, step, like, src, action="rewind",
                    expect_ranks=[0], reason=iact.reason,
                    what="integrity")
                restored, mf = restored_pair
            else:
                dec = None
                restored, mf = policy.rewind(step, like, src,
                                             reason=iact.reason)
            params, gs, ist = (restored["params"], restored["gs"],
                               restored["ist"])
            # restore re-replicates from the saved logical value —
            # prove replica agreement before training resumes
            mn, mx, _ = verify_fn(params)
            assert int(mn) == int(mx), "post-rewind replicas disagree"
            it_box[0] = None
            rewound_at.append((step, int(mf["step"])))
            rewound = True
        elif iact.kind == "escalate":
            raise AssertionError(f"unexpected integrity escalation at "
                                 f"step {step}: {iact}")
        if step % SAVE_EVERY == 0 and not rewound:
            mgr.save(step, {"params": params, "gs": gs, "ist": ist},
                     extra={"cursor": src.state()})
            mgr.wait()
    src.close()
    logger.close()
    if member is not None:
        member.leave()
    return {"losses": losses, "params": params, "gs": gs, "ist": ist,
            "policy": policy, "events_path": events_path,
            "ievents_path": ievents_path, "repaired_at": repaired_at,
            "rewound_at": rewound_at,
            "final_cursor_index": src.cursor_index()}


def _hierarchical_leg():
    """Claim (d): the EF-int8 hierarchical schedule keeps params and
    post-sync grads bitwise identical on every replica — fingerprints
    fold BOTH, every step, over the factored (2-slice × 4) mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex_tpu import guard
    from apex_tpu.lint.mesh_model import parse_mesh_spec
    from apex_tpu.parallel import (DATA_INTER_AXIS, DATA_INTRA_AXIS,
                                   hierarchy, hierarchical_data_mesh)

    AX = (DATA_INTER_AXIS, DATA_INTRA_AXIS)
    mesh = hierarchical_data_mesh(4)
    dim, lr, steps = 512, 0.4, 20
    rng = np.random.RandomState(7)
    targets = jnp.asarray(rng.randn(8, dim) * 3.0, jnp.float32)
    t_mean = np.mean(np.asarray(targets), axis=0)
    plan = hierarchy.plan_comm(parse_mesh_spec("dp2x4"),
                               grad_bytes=dim * 4, compress_block=64)
    assert plan.is_hierarchical
    icfg = guard.IntegrityConfig(check_every=1)

    def step(w, r, ist, t):
        g = {"w": w - t[0]}
        out, r2 = hierarchy.hierarchical_sync(g, plan,
                                              residual={"w": r[0]})
        # fold the committed params AND the post-sync grads: the
        # invariant covers both, and the grads half is the direct
        # runtime proof of the compressed collective itself
        ist = guard.integrity_check(ist, icfg, {"w": w}, axis_name=AX,
                                    grads=out)
        return w - lr * out["w"], r2["w"][None], ist

    jstep = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(AX), P(), P(AX)),
        out_specs=(P(), P(AX), P()), check_vma=False))
    w = jnp.zeros((dim,), jnp.float32)
    r = jnp.zeros((8, dim), jnp.float32)
    ist = guard.integrity_init(icfg, world=8)
    for _ in range(steps):
        w, r, ist = jstep(w, r, ist, targets)
    n_checks = int(np.asarray(ist.check_count))
    n_mismatch = int(np.asarray(ist.mismatch_count))
    assert n_checks == steps, (n_checks, steps)
    assert n_mismatch == 0, \
        f"EF-int8 hierarchical sync diverged replicas ({n_mismatch} " \
        f"of {n_checks} checks mismatched)"
    err = float(np.linalg.norm(np.asarray(w) - t_mean)
                / np.linalg.norm(t_mean))
    assert err < 0.05, err
    return n_checks, err


def main_audit():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from apex_tpu import guard
    from apex_tpu.data.pipeline import make_fake_imagefolder
    from apex_tpu.monitor.check import module_count_and_host_ops

    devs = jax.devices()
    if len(devs) < 8:
        raise SystemExit("audit needs 8 devices — pass --cpu8 for the "
                         "8-device virtual mesh")
    mesh8 = Mesh(np.array(devs[:8]), ("data",))
    cfg, icfg = _make_cfg()
    jstep8 = _make_step(cfg, icfg, mesh8, "data")

    tmp = tempfile.mkdtemp(prefix="apex_integrity_audit_")
    imgroot = make_fake_imagefolder(os.path.join(tmp, "imgs"),
                                    n_classes=4, per_class=8, size=64,
                                    seed=0)

    # --- (a) clean fingerprinted run: zero events, bit-identical HLO ---------
    import jax.numpy as jnp
    params0 = _init_params(mesh8)
    gs0 = guard.guard_init(cfg)
    ist0 = guard.integrity_init(icfg, world=8)
    x0 = jnp.zeros((BATCH, IMG, IMG, 3), jnp.float32)
    y0 = jnp.zeros((BATCH,), jnp.int32)
    hlo_before = jstep8.lower(params0, gs0, ist0, x0,
                              y0).compile().as_text()
    clean = run_guarded(imgroot, tmp, jstep8, cfg, icfg, mesh8, "data",
                        tag="clean")
    hlo_after = jstep8.lower(params0, gs0, ist0, x0,
                             y0).compile().as_text()
    assert hlo_after == hlo_before, \
        "integrity observation changed the compiled step"
    _n, host = module_count_and_host_ops(jstep8, params0, gs0, ist0,
                                         x0, y0)
    assert not host, f"fingerprinted step compiled host traffic: {host}"
    for path in (clean["events_path"], clean["ievents_path"]):
        with open(path) as f:
            evs = [l for l in f if l.strip()]
        assert not evs, f"clean run emitted events in {path}: {evs[:3]}"
    assert int(np.asarray(clean["ist"].mismatch_count)) == 0
    assert int(np.asarray(clean["ist"].check_count)) == \
        (N_STEPS + CHECK_EVERY - 1) // CHECK_EVERY
    assert clean["policy"].repairs_done == 0
    assert clean["policy"].rewinds_done == 0
    print(f"  (a) clean run: {N_STEPS} steps, "
          f"{int(np.asarray(clean['ist'].check_count))} fingerprint "
          f"checks, 0 mismatches, 0 integrity/guard events; compiled "
          f"HLO bit-identical under observation, no host ops")

    # --- (b) silent mantissa bitflip on replica 1 → in-place repair ----------
    # flipped AFTER step 3 commits; the step-4 check (cadence 2)
    # catches it: detection latency 1 <= check_every. The polluted
    # step-4 update is vetoed in-graph on EVERY replica, so the
    # majority's params are still the bitwise post-step-3 state — the
    # repair broadcast makes all replicas exactly that, and the oracle
    # (which never consumed step 4's batch) must match bitwise from
    # step 5 on. NO checkpoint is touched.
    plan_b = guard.FaultPlan(seed=1).add(3, "params",
                                         "bitflip_mantissa", arg=12)
    faulted = run_guarded(imgroot, tmp, jstep8, cfg, icfg, mesh8,
                          "data", plan=plan_b, replica=1,
                          tag="bitflip")
    assert faulted["repaired_at"] == [4], faulted["repaired_at"]
    assert faulted["rewound_at"] == [], faulted["rewound_at"]
    assert faulted["policy"].rewinds_done == 0, \
        "repair must not touch the checkpoint ladder"
    gsf = faulted["gs"]
    assert int(np.asarray(gsf.nonfinite_param_count)) == 0, \
        "the mantissa flip must be silent to the nonfinite-param probe"
    assert int(np.asarray(gsf.spike_count)) == 0, \
        "the mantissa flip must be silent to the spike detector"
    assert int(np.asarray(gsf.replica_divergence_count)) == 1
    assert int(np.asarray(gsf.skip_count)) == 1
    vote = faulted["policy"].last_vote
    assert vote.minority == (1,) and vote.source_rank == 0, vote
    with open(faulted["ievents_path"]) as f:
        ik = [json.loads(l)["kind"] for l in f if l.strip()]
    assert ik == ["integrity_check", "integrity_vote",
                  "integrity_repair"], ik

    oracle = run_guarded(imgroot, tmp, jstep8, cfg, icfg, mesh8,
                         "data", oracle_skip=(4, 1), tag="oracle_b",
                         n_steps=N_STEPS - 1)
    f_tail = [l.tobytes().hex() for l in faulted["losses"][5:]]
    o_tail = [l.tobytes().hex() for l in oracle["losses"][4:]]
    assert f_tail == o_tail, (
        "post-repair losses diverge from the fault-free oracle: "
        f"{list(zip(f_tail, o_tail))}")
    for k in ("w", "b"):
        a = np.asarray(faulted["params"][k])
        b = np.asarray(oracle["params"][k])
        assert np.array_equal(a, b), f"final params[{k}] not bitwise"
    # ... on EVERY replica's buffer, not just the logical view
    for sh in faulted["params"]["w"].addressable_shards:
        assert np.array_equal(np.asarray(sh.data),
                              np.asarray(oracle["params"]["w"]))
    assert (faulted["final_cursor_index"]
            == oracle["final_cursor_index"])
    print(f"  (b) silent bitflip (mantissa bit 12, replica 1, step 3):"
          f" detected at step 4 (within check_every={CHECK_EVERY}), "
          f"minority [1] named, repaired in place from replica 0 with "
          f"NO rewind; {len(f_tail)} post-repair losses + final params"
          f" (all replica buffers) BITWISE == fault-free oracle")

    # --- (c) both replicas diverge → no majority → coordinated rewind --------
    mesh2 = Mesh(np.array(devs[:2]), ("data",))
    jstep2 = _make_step(cfg, icfg, mesh2, "data")
    both = run_guarded(imgroot, tmp, jstep2, cfg, icfg, mesh2, "data",
                       diverge_both_at=3, tag="nomajority",
                       coordinator_dir=os.path.join(tmp, "cluster_c"))
    assert both["repaired_at"] == [], both["repaired_at"]
    assert both["rewound_at"] == [(4, 2)], both["rewound_at"]
    assert both["policy"].rewinds_done == 1
    with open(both["ievents_path"]) as f:
        iev = [json.loads(l) for l in f if l.strip()]
    votes = [e for e in iev if e["kind"] == "integrity_vote"]
    assert len(votes) == 1 and votes[0]["action"] == "rewind", votes
    assert votes[0]["source_rank"] is None, \
        "a no-majority vote has no broadcast source"
    assert not any(e["kind"] == "integrity_repair" for e in iev)
    # exactly one generation bump, attributed to the integrity round
    gens = os.listdir(os.path.join(tmp, "cluster_c"))
    bumps = sorted(n for n in gens if n.startswith("generation."))
    # epoch 0 is implicit (no file); EXACTLY one committed bump
    assert bumps == ["generation.00000001.json"], bumps
    oracle_c = run_guarded(imgroot, tmp, jstep2, cfg, icfg, mesh2,
                           "data", oracle_skip=(3, 2), tag="oracle_c",
                           n_steps=N_STEPS - 2)
    f_tail = [l.tobytes().hex() for l in both["losses"][5:]]
    o_tail = [l.tobytes().hex() for l in oracle_c["losses"][3:]]
    assert f_tail == o_tail, "post-rewind losses diverge from oracle"
    for k in ("w", "b"):
        assert np.array_equal(np.asarray(both["params"][k]),
                              np.asarray(oracle_c["params"][k]))
    print(f"  (c) 2-of-2 divergence (dp=2, both replicas flipped): no "
          f"majority — escalated to the coordinated-rewind path "
          f"(generation bumped once, target step 2), NOT repaired; "
          f"post-rewind losses + final params BITWISE == oracle")

    # --- (d) EF-int8 hierarchical sync is fingerprint-clean ------------------
    n_checks, err = _hierarchical_leg()
    print(f"  (d) EF-int8 hierarchical sync: {n_checks}/{n_checks} "
          f"per-step fingerprint checks clean (params + post-sync "
          f"grads bitwise identical across all 8 replicas), "
          f"trajectory converged (rel err {err:.4f}) — the "
          f"collectives-v2 runtime proof")

    # --- (e) event streams validate ------------------------------------------
    from scripts.check_metrics_schema import (check_guard_lines,
                                              check_integrity_lines)
    n_events = 0
    for res in (faulted, both):
        with open(res["ievents_path"]) as f:
            errors = check_integrity_lines(f)
        assert not errors, ("integrity event schema violations:\n"
                            + "\n".join(errors))
        with open(res["events_path"]) as f:
            errors = check_guard_lines(f)
        assert not errors, ("guard event schema violations:\n"
                            + "\n".join(errors))
        with open(res["ievents_path"]) as f:
            n_events += sum(1 for l in f if l.strip())
    print(f"  (e) {n_events} integrity events validate "
          f"(--kind integrity); guard streams stay valid")
    print("integrity audit ok")


def main():
    if "--cpu8" in sys.argv:
        import jax
        from apex_tpu import _compat
        jax.config.update("jax_platforms", "cpu")
        _compat.request_cpu_devices(8)
    main_audit()


if __name__ == "__main__":
    main()
