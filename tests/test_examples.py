"""Examples smoke tests on the 8-device CPU mesh.

The reference's L1 harness drives a clone of the imagenet example
(`tests/L1/common/main_amp.py`); here the *actual* example entry points
run in-process on the virtual mesh — every example must work both
single-chip and distributed (VERDICT round-1 requirement #4).
"""

import importlib.util
import os
import sys

import pytest

_EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run_example(rel_path, argv):
    path = os.path.abspath(os.path.join(_EXAMPLES, rel_path))
    spec = importlib.util.spec_from_file_location(
        "example_" + os.path.basename(rel_path)[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    old = sys.argv
    sys.argv = [path] + argv
    try:
        mod.main()
    finally:
        sys.argv = old


def test_simple_distributed(devices):
    _run_example("simple/distributed/distributed_data_parallel.py",
                 ["--steps", "3"])


@pytest.mark.slow           # ~90s pair on CPU CI; dcgan + simple stay tier-1
@pytest.mark.parametrize("extra", [
    [],                                   # plain O2
    ["--sync_bn", "--opt-level", "O1"],   # syncbn + O1 policy
])
def test_imagenet(devices, extra, capsys):
    _run_example("imagenet/main_amp.py",
                 ["-b", "16", "--steps-per-epoch", "2", "--image-size", "32",
                  "--arch", "resnet18", "--print-freq", "2"] + extra)
    out = capsys.readouterr().out
    assert "img/s" in out


def test_dcgan(devices):
    _run_example("dcgan/main_amp.py",
                 ["--niter", "2", "--batchSize", "8", "--ngf", "16",
                  "--ndf", "16", "--print-freq", "2"])


@pytest.mark.slow           # ~30s on CPU CI: JPEG tree + pipeline end-to-end
def test_imagenet_real_data(devices, tmp_path, capsys):
    """--data: train from an actual JPEG ImageFolder tree through the
    apex_tpu.data pipeline (loader probe + prefetch + sharded step)."""
    pytest.importorskip("PIL")
    from apex_tpu.data import make_fake_imagefolder

    make_fake_imagefolder(str(tmp_path), n_classes=2, per_class=10,
                          size=48)
    _run_example("imagenet/main_amp.py",
                 ["--data", str(tmp_path), "-b", "16",
                  "--steps-per-epoch", "2", "--image-size", "32",
                  "--arch", "resnet18", "--print-freq", "2",
                  "--loader-workers", "2"])
    out = capsys.readouterr().out
    assert "loader:" in out and "img/s" in out
