"""apex_tpu.trace — spans, flight recorder, watchdog, NaN provenance.

Covers the ISSUE-2 acceptance contract: the span timeline produces a
structurally valid Chrome trace (Perfetto-loadable), a forced mid-step
exception in a subprocess produces a crash dump naming the
last-completed span with a valid Metrics snapshot (validated by
``scripts/check_metrics_schema.py --kind trace``), a stalled step fires
the hang watchdog with thread stacks, ``debug_nans`` names the first
non-finite span, and spans/probes with the mode off add zero extra
dispatches to the compiled step.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, monitor, trace
from apex_tpu.optim import FusedSGD

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_SCHEMA_SCRIPT = os.path.join(_REPO_ROOT, "scripts",
                              "check_metrics_schema.py")


def _validate(path, kind):
    return subprocess.run(
        [sys.executable, _SCHEMA_SCRIPT, "--kind", kind, str(path)],
        capture_output=True, text=True, cwd=_REPO_ROOT)


# --- span timeline -----------------------------------------------------------

def test_span_timeline_records_steps_and_nesting():
    tracer = trace.Tracer()
    with tracer:
        for i in range(3):
            with trace.step(i):
                with trace.span("fwd"):
                    time.sleep(0.002)
                    with trace.span("inner"):
                        pass
                with trace.span("bwd"):
                    pass
    assert len(tracer.steps) == 3
    st = tracer.steps[0]
    assert [s.name for s in st.spans] == ["inner", "fwd", "bwd"]
    fwd = next(s for s in st.spans if s.name == "fwd")
    inner = next(s for s in st.spans if s.name == "inner")
    assert fwd.dur_ms >= 2.0              # slept 2ms inside
    assert inner.depth == fwd.depth + 1   # nesting tracked
    assert st.dur_ms >= fwd.dur_ms
    assert tracer.last_completed_span == "bwd"
    # table has one column per span name, one row per step
    table = tracer.timeline().table()
    for col in ("fwd", "inner", "bwd", "total_ms"):
        assert col in table
    assert len(table.splitlines()) == 4


def test_span_passive_without_tracer():
    # no tracer entered: span still works (named_scope passthrough)
    with trace.span("orphan"):
        x = jnp.ones(3) * 2
    assert trace.current_tracer() is None
    assert float(x[0]) == 2.0


def test_span_decorator_and_annotate_feed_timeline():
    from apex_tpu import prof

    @trace.span("work")
    def work(x):
        return x + 1

    @prof.annotate("annotated")
    def annotated(x):
        return x * 2

    tracer = trace.Tracer()
    with tracer:
        with trace.step():
            work(jnp.ones(2))
            annotated(jnp.ones(2))
    names = [s.name for s in tracer.steps[0].spans]
    assert names == ["work", "annotated"]


def test_in_flight_collective_and_open_spans():
    tracer = trace.Tracer()
    with tracer:
        with trace.step():
            with trace.span("outer"):
                with trace.span("allreduce", kind="collective"):
                    assert tracer.open_spans == ["outer", "allreduce"]
                    assert tracer.in_flight_collective == "allreduce"
            assert tracer.in_flight_collective is None


def test_recovered_exception_clears_in_flight():
    """A span unwound by a caught-and-recovered exception must not be
    reported in-flight once the step completes normally."""
    tracer = trace.Tracer()
    with tracer:
        with trace.step(0):
            try:
                with trace.span("load", kind="collective"):
                    raise IOError("transient")
            except IOError:
                pass
            # mid-step: the aborted span IS still in flight forensically
            assert tracer.in_flight_collective == "load"
            with trace.span("work"):
                pass
        # the step completed: nothing is in flight any more
        assert tracer.open_spans == []
        assert tracer.in_flight_collective is None
        assert tracer.last_completed_span == "work"


def test_chrome_trace_is_structurally_valid(tmp_path):
    """The Perfetto-loadability contract: JSON object with a traceEvents
    list of complete-duration events (name/ph/ts/dur/pid/tid), as the
    Trace Event Format requires."""
    tracer = trace.Tracer()
    with tracer:
        for i in range(2):
            with trace.step(i):
                with trace.span("a"):
                    with trace.span("b"):
                        pass
    path = tmp_path / "trace.json"
    tracer.write_chrome_trace(str(path), rank=0)
    ct = json.loads(path.read_text())
    assert isinstance(ct, dict)
    all_evs = ct["traceEvents"]
    evs = [e for e in all_evs if e["ph"] == "X"]
    assert isinstance(all_evs, list) and len(evs) == 6  # 2 steps + 4 spans
    for ev in evs:
        assert isinstance(ev["name"], str) and ev["name"]
        for k in ("ts", "dur"):
            assert isinstance(ev[k], (int, float)) and ev[k] >= 0
        for k in ("pid", "tid"):
            assert isinstance(ev[k], int)
    # metadata ("ph": "M") events label the rank track (tested in
    # detail by test_chrome_trace_carries_rank_metadata)
    assert any(e["ph"] == "M" for e in all_evs)
    # events nest consistently: child ts within parent [ts, ts+dur]
    spans = [e for e in evs if e["cat"] != "step"]
    a = [e for e in spans if e["name"] == "a"][0]
    b = [e for e in spans if e["name"] == "b"][0]
    assert a["ts"] <= b["ts"] <= b["ts"] + b["dur"] <= a["ts"] + a["dur"] \
        + 1e3  # 1ms slack for clock reads


def test_chrome_trace_carries_rank_metadata():
    """The multi-rank merge contract (ISSUE-9 satellite): every rank's
    export tags its events with pid=rank AND labels the track with
    process_name/process_sort_index metadata — so concatenating N
    ranks' traceEvents yields N distinct, labeled, sorted Perfetto
    tracks instead of anonymous colliding ones. StepTimeline's event
    exports carry the same rank on every record."""
    def one_rank(r):
        tracer = trace.Tracer()
        with tracer:
            with trace.step(0):
                with trace.span("fwd"):
                    pass
        return tracer

    tracers = {r: one_rank(r) for r in (0, 3)}
    merged = []
    for r, tr in tracers.items():
        ct = tr.chrome_trace(rank=r)
        assert ct["metadata"]["rank"] == r
        merged.extend(ct["traceEvents"])
    names = {e["pid"]: e["args"]["name"] for e in merged
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {0: "rank 0", 3: "rank 3"}
    sort = {e["pid"]: e["args"]["sort_index"] for e in merged
            if e["ph"] == "M" and e["name"] == "process_sort_index"}
    assert sort == {0: 0, 3: 3}
    # every duration event rides its rank's pid track — no collisions
    for r in (0, 3):
        rank_evs = [e for e in merged if e["ph"] == "X"
                    and e["pid"] == r]
        assert {e["name"] for e in rank_evs} == {"step 0", "fwd"}
    # the JSONL exports carry the rank field per record too
    for r, tr in tracers.items():
        assert all(ev["rank"] == r for ev in tr.step_events(rank=r))
        assert all(ev["rank"] == r for ev in tr.span_events(rank=r))


def test_trace_schema_rejects_malformed_values():
    from importlib import util as _util
    spec = _util.spec_from_file_location("check_metrics_schema",
                                        _SCHEMA_SCRIPT)
    mod = _util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    ok = {"kind": "span", "name": "x", "step": 0, "rank": 0,
          "t_ms": 1.0, "dur_ms": 2.0}
    assert mod.check_trace_lines([json.dumps(ok)]) == []
    # non-numeric duration
    bad = dict(ok, dur_ms="fast")
    assert mod.check_trace_lines([json.dumps(bad)])
    # null on a non-nullable key
    bad = dict(ok, t_ms=None)
    assert mod.check_trace_lines([json.dumps(bad)])
    # negative duration / unknown kind / missing required key
    assert mod.check_trace_lines([json.dumps(dict(ok, dur_ms=-1.0))])
    assert mod.check_trace_lines([json.dumps(dict(ok, kind="nope"))])
    no_name = dict(ok)
    no_name.pop("name")
    assert mod.check_trace_lines([json.dumps(no_name)])


def test_step_events_pass_trace_schema(tmp_path):
    tracer = trace.Tracer()
    with tracer:
        for i in range(2):
            with trace.step(i):
                with trace.span("s"):
                    pass
    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        for ev in tracer.step_events(rank=0) + tracer.span_events(rank=0):
            f.write(json.dumps(ev) + "\n")
    r = _validate(path, "trace")
    assert r.returncode == 0, r.stderr


def test_metrics_logger_trace_event_channel(tmp_path):
    events = tmp_path / "events.jsonl"
    logger = monitor.MetricsLogger(
        sinks=[], trace_sink=monitor.JSONLSink(str(events)))
    tracer = trace.Tracer()
    tracer.subscribe(lambda st: logger.record_event(st.to_event(0)))
    with tracer:
        with trace.step(7):
            with trace.span("x"):
                pass
    logger.close()
    recs = [json.loads(l) for l in events.read_text().splitlines()]
    assert len(recs) == 1
    assert recs[0]["kind"] == "step" and recs[0]["step"] == 7
    assert recs[0]["spans"][0]["name"] == "x"
    assert _validate(events, "trace").returncode == 0


# --- MetricsLogger crash-safety (satellite) ----------------------------------

def test_metrics_logger_flushes_buffered_tail_on_exception(tmp_path):
    jsonl = tmp_path / "m.jsonl"
    with pytest.raises(RuntimeError):
        with monitor.MetricsLogger(
                sinks=[monitor.JSONLSink(str(jsonl))],
                flush_every=100) as logger:
            m = monitor.metrics_init().count_step(jnp.bool_(True))
            logger.record(m)           # buffered, below flush_every
            raise RuntimeError("mid-run crash")
    lines = jsonl.read_text().splitlines()
    assert len(lines) == 1             # the tail reached the sink
    assert json.loads(lines[0])["step"] == 1


def test_metrics_logger_atexit_flush_in_subprocess(tmp_path):
    jsonl = tmp_path / "m.jsonl"
    child = textwrap.dedent(f"""
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        from apex_tpu import monitor
        logger = monitor.MetricsLogger(
            sinks=[monitor.JSONLSink({str(jsonl)!r})], flush_every=100)
        m = monitor.metrics_init().count_step(jnp.bool_(True))
        logger.record(m)
        # no close(): the atexit hook must flush the buffered record
    """)
    r = subprocess.run([sys.executable, "-c", child], cwd=_REPO_ROOT,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr
    assert len(jsonl.read_text().splitlines()) == 1


# --- NaN provenance ----------------------------------------------------------

def test_debug_nans_names_first_bad_span():
    trace.reset_nan_state()
    with trace.debug_nans():
        @jax.jit
        def f(x):
            a = trace.nan_probe("scale", x * 2)         # finite
            b = trace.nan_probe("log", jnp.log(-a))     # nan
            return trace.nan_probe("sum", jnp.sum(b))   # nan too

        out = f(jnp.ones(4))
        jax.block_until_ready(out)
    hit = trace.first_nan()
    assert hit is not None and hit["span"] == "log"
    trace.reset_nan_state()
    assert trace.first_nan() is None


def test_debug_nans_off_is_identity_and_dispatch_free():
    def traced(w, x):
        with trace.span("fwd"):
            h = jnp.tanh(x @ w)
        h = trace.nan_probe("fwd", h)
        return trace.nan_probe("loss", jnp.sum(h * h))

    def plain(w, x):
        h = jnp.tanh(x @ w)
        return jnp.sum(h * h)

    w = jnp.ones((8, 4)) * 0.1
    x = jnp.ones((2, 8))
    n_t, host_t = monitor.module_count_and_host_ops(
        jax.jit(traced), w, x)
    n_p, _ = monitor.module_count_and_host_ops(jax.jit(plain), w, x)
    assert n_t == n_p
    assert host_t == [], host_t
    # and ON compiles real host callbacks (the guard is load-bearing).
    # The flag is read at trace time and jax caches traces per function
    # object — exactly the documented caveat — so drop the cached trace
    with trace.debug_nans():
        jax.clear_caches()
        _, host_on = monitor.module_count_and_host_ops(
            jax.jit(traced), w, x)
    assert host_on
    trace.reset_nan_state()


def test_amp_builtin_probes_name_fwd_span():
    """A loss that is non-finite at the forward pass must be attributed
    to amp/fwd — the built-in provenance of the amp step."""
    trace.reset_nan_state()
    params = {"w": jnp.full((4, 2), 0.5, jnp.float32)}
    amp_opt, state = amp.initialize(params, FusedSGD(lr=0.1), "O2",
                                    half_dtype=jnp.float16, verbosity=0)
    x = jnp.ones((4, 4), jnp.float32)

    with trace.debug_nans():
        @jax.jit
        def step(state):
            def loss_fn(p):
                return jnp.log(-jnp.abs(jnp.mean(x @ p["w"])))   # nan
            state, loss, finite = amp_opt.step(state, loss_fn)
            return state, loss

        state, loss = step(state)
        jax.block_until_ready(loss)
    hit = trace.first_nan()
    assert hit is not None and hit["span"] == "amp/fwd", hit
    trace.reset_nan_state()


# --- flight recorder ---------------------------------------------------------

def test_recorder_ring_is_bounded_and_ranked_path(tmp_path):
    rec = trace.FlightRecorder(str(tmp_path / "c.jsonl"), capacity=3)
    for i in range(10):
        rec.record(step=i, dur_ms=1.0, spans=[("s", 0.5)])
    p = rec.dump(reason="manual")
    lines = [json.loads(l) for l in open(p)]
    assert lines[0]["kind"] == "crash"
    steps = [l["step"] for l in lines[1:]]
    assert steps == [7, 8, 9]            # only the last `capacity` kept
    # rank_path: identity single-process, ranked when explicit
    assert trace.rank_path("a/b.jsonl", rank=3) == "a/b.rank3.jsonl"
    assert trace.rank_path(str(tmp_path / "x.jsonl")) == \
        str(tmp_path / "x.jsonl")


def test_recorder_dump_passes_trace_schema(tmp_path):
    tracer = trace.Tracer()
    rec = trace.FlightRecorder(str(tmp_path / "c.jsonl"), tracer=tracer,
                               collective_bytes=4096)
    m = monitor.metrics_init().count_step(jnp.bool_(True))
    with tracer:
        with trace.step(0):
            with trace.span("fwd"):
                pass
        rec.record_metrics(m)
    p = rec.dump(reason="manual")
    r = _validate(p, "trace")
    assert r.returncode == 0, r.stderr
    lines = [json.loads(l) for l in open(p)]
    assert lines[0]["last_completed_span"] == "fwd"
    step_rec = lines[1]
    assert step_rec["metrics"]["step"] == 1
    assert step_rec["collective_bytes"] == 4096


_CRASH_CHILD = textwrap.dedent("""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import sys
    import jax.numpy as jnp
    from apex_tpu import amp, trace
    from apex_tpu.optim import FusedSGD

    tracer = trace.Tracer()
    rec = trace.FlightRecorder(sys.argv[1], capacity=8, tracer=tracer)
    rec.install()

    params = {"w": jnp.full((4, 2), 0.5, jnp.float32)}
    amp_opt, state = amp.initialize(params, FusedSGD(lr=0.1), "O1",
                                    verbosity=0, monitor=True)
    x = jnp.ones((4, 4), jnp.float32)

    @jax.jit
    def step(state):
        def loss_fn(p):
            return jnp.mean(x @ p["w"])
        state, loss, _ = amp_opt.step(state, loss_fn)
        return state, loss

    with tracer:
        for i in range(3):
            with trace.step(i):
                with trace.span("dispatch"):
                    state, loss = step(state)
                with trace.span("fetch"):
                    float(loss)
                rec.record_metrics(state.metrics)
        # step 3 dies mid-step, after fwd completed, inside bwd
        with trace.step(3):
            with trace.span("fwd"):
                pass
            with trace.span("bwd"):
                raise RuntimeError("boom mid-step")
""")


def test_forced_midstep_exception_dumps_crash_report(tmp_path):
    """The acceptance case (single-process half): a raise mid-step
    leaves a crash dump whose header names the last-completed span and
    the in-flight one, whose step records carry valid Metrics
    snapshots, and which passes the trace schema validator."""
    dump = tmp_path / "crash.jsonl"
    r = subprocess.run([sys.executable, "-c", _CRASH_CHILD, str(dump)],
                       cwd=_REPO_ROOT, capture_output=True, text=True,
                       timeout=240)
    assert r.returncode != 0                      # it crashed...
    assert "boom mid-step" in r.stderr            # ...loudly (hook chained)
    assert dump.exists(), r.stderr
    lines = [json.loads(l) for l in dump.read_text().splitlines()]
    hdr = lines[0]
    assert hdr["kind"] == "crash" and hdr["reason"] == "exception"
    assert hdr["last_completed_span"] == "fwd"    # fwd done, bwd open
    assert "bwd" in hdr["in_flight_spans"]
    assert "RuntimeError" in hdr["exception"]
    assert hdr["traceback"]
    # buffered steps carry fetched Metrics snapshots; the dying step is
    # recorded too, flagged aborted
    steps = [l for l in lines[1:] if l["kind"] == "step"]
    assert len(steps) == 4
    assert [s["metrics"]["step"] for s in steps[:3]] == [1, 2, 3]
    assert all(s["metrics"]["loss_scale"] is not None for s in steps[:3])
    assert steps[3]["aborted"] is True and steps[3].get("metrics") is None
    # the artifact validates
    assert _validate(dump, "trace").returncode == 0


# --- hang watchdog -----------------------------------------------------------

def test_watchdog_fires_on_stalled_step_and_dump_validates(tmp_path):
    tracer = trace.Tracer()
    rec = trace.FlightRecorder(str(tmp_path / "c.jsonl"), tracer=tracer)
    fired = []
    wd = trace.HangWatchdog(0.15, recorder=rec, tracer=tracer,
                            path=str(tmp_path / "hang.jsonl"),
                            on_fire=fired.append, poll_s=0.02)
    with tracer:
        with wd:
            # two healthy steps, then a stall longer than the deadline
            for i in range(2):
                with trace.step(i):
                    with trace.span("work"):
                        pass
            assert wd.fire_count == 0
            time.sleep(0.5)              # artificially stalled step
    assert wd.fire_count == 1            # fired once, not per poll
    ev = fired[0]
    assert ev["kind"] == "watchdog"
    assert ev["last_step"] == 1
    assert ev["seconds_since_last_step"] >= 0.15
    assert ev["silent_ranks"] == [ev["rank"]]
    assert ev["last_completed_span"] == "work"
    # the stack dump contains this (stalled) test frame
    stacks = "\n".join("\n".join(v) for v in ev["stacks"].values())
    assert "test_watchdog_fires_on_stalled_step" in stacks
    assert _validate(tmp_path / "hang.jsonl", "trace").returncode == 0


def test_watchdog_path_not_double_ranked_and_skips_device_fetch(tmp_path):
    """The derived hang path must not re-apply the rank suffix the
    recorder's path already carries, and the hang dump must not fetch
    device metrics (a device_get against a wedged runtime blocks the
    watchdog thread forever)."""
    ranked = str(tmp_path / "crash.rank0.jsonl")   # as on a multi-host run
    rec = trace.FlightRecorder(ranked)
    rec.record(step=0, metrics=monitor.metrics_init())
    wd = trace.HangWatchdog(30.0, recorder=rec)
    assert wd.path == str(tmp_path / "crash.rank0.hang.jsonl")
    wd.fire(idle_s=31.0)                           # manual fire, no thread
    lines = [json.loads(l) for l in open(wd.path)]
    step_rec = [l for l in lines if l["kind"] == "step"][0]
    assert step_rec["metrics"] is None             # buffered, NOT fetched
    assert step_rec["metrics_error"]
    assert _validate(wd.path, "trace").returncode == 0


def test_watchdog_rearms_after_heartbeat_resumes(tmp_path):
    wd = trace.HangWatchdog(0.1, path=str(tmp_path / "h.jsonl"),
                            poll_s=0.02)
    wd.start()
    time.sleep(0.3)
    assert wd.fire_count == 1
    wd.notify_step(5)                    # heartbeat resumes
    time.sleep(0.3)                      # second stall
    wd.stop()
    assert wd.fire_count == 2
    ev = json.loads(open(tmp_path / "h.jsonl").readline())
    assert ev["last_step"] == 5


# --- multi-process acceptance case -------------------------------------------

_MP_CHILD = textwrap.dedent("""
    import os, sys
    import jax
    from apex_tpu import _compat
    jax.config.update("jax_platforms", "cpu")
    _compat.request_cpu_devices(2)

    from apex_tpu.parallel.launch import distributed_init, \\
        enable_crash_dumps

    distributed_init()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from apex_tpu import parallel, trace
    from apex_tpu.parallel import DistributedDataParallel

    tracer, rec, _wd, _cd = enable_crash_dumps(sys.argv[1], capacity=8)

    mesh = parallel.data_parallel_mesh()
    ddp = DistributedDataParallel(mesh)

    def step(w, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(w)
        g = ddp.sync(g)
        return w - 0.1 * g, jax.lax.pmean(loss, ddp.axis_name)

    spmd = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(parallel.DATA_AXIS), P(parallel.DATA_AXIS)),
        out_specs=(P(), P()), check_vma=False))

    np_rng = np.random.RandomState(0)
    w = jnp.asarray(np_rng.randn(8, 1), jnp.float32)
    xg = np_rng.randn(16, 8).astype("float32")
    yg = np_rng.randn(16, 1).astype("float32")

    try:
        xs = jax.device_put(xg, parallel.batch_sharding(mesh))
        ys = jax.device_put(yg, parallel.batch_sharding(mesh))

        def dispatch(w):
            return spmd(w, xs, ys)

        w2, loss = dispatch(w)
        float(loss)
        w = w2
        start = 1
    except Exception as e:
        if "Multiprocess computations aren't implemented" not in str(e):
            raise
        # this CPU backend can form the 2-process cluster but cannot run
        # cross-process programs; the crash-dump contract under test
        # (per-rank files, rank tagging, span forensics) doesn't need
        # the psum — fall back to a process-local step
        local = jax.jit(lambda w: (
            w - 0.1 * jax.grad(lambda w: jnp.mean((xg @ w - yg) ** 2))(w),
            jnp.mean((xg @ w - yg) ** 2)))

        def dispatch(w):
            return local(w)
        start = 0

    with tracer:
        for i in range(start, 2):
            with trace.step(i):
                with trace.span("dispatch"):
                    w, loss = dispatch(w)
                with trace.span("fetch"):
                    float(loss)
        with trace.step(2):
            with trace.span("dispatch"):
                w, loss = dispatch(w)
            raise RuntimeError(f"forced mid-step crash on rank {rank}")
""")


@pytest.mark.slow
def test_two_process_crash_produces_per_rank_dumps(tmp_path):
    """The ISSUE-2 acceptance case: a forced mid-step exception in a
    2-process parallel.launch run produces per-rank crash dumps that
    name the last-completed span and pass the extended schema
    validator."""
    import socket

    def _free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    base = tmp_path / "crash.jsonl"
    env_base = {
        **os.environ,
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(_free_port()),
        "WORLD_SIZE": "2",
        "JAX_PLATFORMS": "cpu",
        "TF_CPP_MIN_LOG_LEVEL": "2",
    }
    procs = []
    for rank in range(2):
        env = {**env_base, "RANK": str(rank)}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _MP_CHILD, str(base)], env=env,
            cwd=_REPO_ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("two-process crash run timed out:\n"
                    + "\n---\n".join(o or "" for o in outs))
    joined = "\n---rank-output---\n".join(outs)
    if not all("forced mid-step crash" in o for o in outs):
        # cluster bring-up unsupported here (same policy as
        # test_multiproc_launch) — but only when the failure is
        # environmental, never when our code broke
        if any(s in joined for s in ("UNAVAILABLE", "DEADLINE_EXCEEDED",
                                     "Permission denied", "unreachable")):
            pytest.skip(f"cluster bring-up unsupported here:\n{joined}")
        pytest.fail(f"children did not reach the forced crash:\n{joined}")
    for rank in range(2):
        dump = tmp_path / f"crash.rank{rank}.jsonl"
        assert dump.exists(), (f"rank {rank} wrote no dump\n{joined}\n"
                               f"{os.listdir(tmp_path)}")
        lines = [json.loads(l) for l in dump.read_text().splitlines()]
        hdr = lines[0]
        assert hdr["kind"] == "crash" and hdr["rank"] == rank
        assert hdr["process_count"] == 2
        assert hdr["last_completed_span"] == "dispatch"
        assert f"rank {rank}" in hdr["exception"]
        steps = [l for l in lines[1:] if l["kind"] == "step"]
        # at least one completed step, then the aborted step 2
        assert len(steps) >= 2 and steps[-1]["step"] == 2
        assert steps[-1].get("aborted") is True
        assert all(not s.get("aborted") for s in steps[:-1])
        assert _validate(dump, "trace").returncode == 0, dump
