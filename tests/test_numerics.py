"""The numerics observatory: per-tensor dynamic-range telemetry, the
per-site delayed-scaling state machine, the precision-placement
advisor, and the ``--kind numerics`` event schema (valid stream +
negative twins). The end-to-end claims — zero-surprise BERT run,
e4m3-boundary flagging with a scale that fixes it, ScaleHistory
bitwise vs its oracle — live in ``scripts/numerics_audit.py --cpu8``.
"""

import hashlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.monitor import numerics as nx

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "bert_numerics_stats.json")

#: CI pin of ``precision_report()`` on the committed BERT fixture: the
#: (fingerprint, required_dtype, recommended_scale) list is a pure
#: host-side function of the committed measurement — regenerate with
#: ``scripts/numerics_audit.py --cpu8 --write-fixture
#: tests/fixtures/bert_numerics_stats.json`` and update the digest
#: ONLY when the verdict machinery intentionally changes.
FIXTURE_VERDICT_DIGEST = \
    "6af8d2d31a7c418c10d12ee11a755d7bd042cc7ff20d0acfc8d1e6d8f0e71dbe"


def _signed_pow2(rng, lo, hi, n=4096):
    return jnp.asarray((2.0 ** rng.uniform(lo, hi, (n,))
                        * np.where(rng.rand(n) < 0.5, -1.0, 1.0)
                        ).astype("float32"))


def _observe_once(trees, weights=None, cfg=nx.NumericsConfig()):
    sites = nx.site_names(trees)
    ns = nx.numerics_init(cfg, sites=sites)
    ns = jax.jit(lambda s: nx.numerics_observe(
        s, cfg, trees, weights=weights))(ns)
    return ns, sites


# --- format table -------------------------------------------------------------

class TestFormatTable:
    def test_ladder_covers_table(self):
        assert set(nx.FORMAT_LADDER) == set(nx.FORMAT_TABLE)

    def test_max_finite_sits_in_top_binade(self):
        for f in nx.FORMAT_TABLE.values():
            assert 2.0 ** f.max_exp <= f.max_finite \
                < 2.0 ** (f.max_exp + 1), f

    def test_known_corners(self):
        assert nx.FORMAT_TABLE["fp8_e4m3"].max_finite == 448.0
        assert nx.FORMAT_TABLE["fp8_e4m3"].min_exp == -6
        assert nx.FORMAT_TABLE["fp8_e5m2"].max_finite == 57344.0
        assert nx.FORMAT_TABLE["fp16"].min_exp == -14

    def test_format_of_dtype(self):
        assert nx.format_of_dtype(jnp.bfloat16) == "bf16"
        assert nx.format_of_dtype("float32") == "fp32"
        assert nx.format_of_dtype(jnp.int32) is None


# --- sites + init -------------------------------------------------------------

class TestSites:
    def test_sorted_prefixes_and_flatten_order(self):
        trees = {"b": {"y": jnp.zeros(2), "x": jnp.zeros(2)},
                 "a": jnp.zeros(3)}
        sites = nx.site_names(trees)
        assert sites[0] == "a"
        assert all(s.startswith("b/") for s in sites[1:])
        assert sites == nx.site_names(dict(reversed(trees.items())))

    def test_init_validation(self):
        with pytest.raises(ValueError):
            nx.numerics_init(nx.NumericsConfig(check_every=0),
                             sites=("a",))
        with pytest.raises(ValueError):
            nx.numerics_init(nx.NumericsConfig(ema=1.5), sites=("a",))
        with pytest.raises(ValueError):
            nx.numerics_init(sites=())


# --- the in-graph fold --------------------------------------------------------

class TestObserve:
    def test_amax_amin_hist_against_numpy(self):
        rng = np.random.RandomState(0)
        x = _signed_pow2(rng, -10, 5)
        ns, sites = _observe_once({"t": x})
        a = np.abs(np.asarray(x))
        assert float(ns.amax[0]) == a.max()
        assert float(ns.amin[0]) == a[a > 0].min()
        hist = np.asarray(ns.exp_hist[0])
        # the histogram is normalized over finite nonzero elements
        assert hist.sum() == pytest.approx(1.0, abs=1e-5)
        # bucket b holds magnitudes in [2^(b-127), 2^(b-126))
        be = (np.frexp(a[a > 0])[1] - 1) + 127
        ref = np.bincount(be, minlength=256) / a.size
        np.testing.assert_allclose(hist, ref, atol=1e-6)

    def test_zero_and_nonfinite_fractions(self):
        x = jnp.asarray([0.0, 0.0, 1.0, np.inf, np.nan, -2.0],
                        jnp.float32)
        ns, _ = _observe_once({"t": x})
        assert float(ns.zero_frac[0]) == pytest.approx(2 / 6)
        assert float(ns.nonfinite_frac[0]) == pytest.approx(2 / 6)
        assert not bool(nx.finite_ok(ns))
        named = nx.nonfinite_sites(ns, ("t",))
        assert named == [("t", pytest.approx(2 / 6))]

    def test_cadence_off_branch(self):
        cfg = nx.NumericsConfig(check_every=3)
        trees = {"t": jnp.ones((4,), jnp.float32)}
        ns = nx.numerics_init(cfg, sites=nx.site_names(trees))
        step = jax.jit(lambda s: nx.numerics_observe(s, cfg, trees))
        for _ in range(7):
            ns = step(ns)
        assert int(ns.step) == 7
        assert int(ns.check_count) == 3          # steps 0, 3, 6
        assert int(ns.last_check_step) == 6

    def test_ema_seeded_by_first_check(self):
        cfg = nx.NumericsConfig(ema=0.5)
        trees = {"t": jnp.full((4,), 8.0, jnp.float32)}
        ns = nx.numerics_init(cfg, sites=("t",))
        ns = nx.numerics_observe(ns, cfg, trees)
        assert float(ns.amax_ema[0]) == 8.0      # no zero-bias warmup
        ns = nx.numerics_observe(ns, cfg,
                                 {"t": jnp.full((4,), 4.0)})
        assert float(ns.amax_ema[0]) == pytest.approx(6.0)

    def test_uw_ratio_companion(self):
        upd = jnp.full((4,), 0.01, jnp.float32)
        w = jnp.full((4,), 1.0, jnp.float32)
        ns, sites = _observe_once({"u": upd}, weights={"u": w})
        assert float(ns.uw_ratio[0]) == pytest.approx(0.01)
        ns2, _ = _observe_once({"u": upd})
        assert float(ns2.uw_ratio[0]) == -1.0    # no companion

    def test_mismatched_trees_refused(self):
        ns = nx.numerics_init(sites=("a", "b"))
        with pytest.raises(ValueError):
            nx.numerics_observe(ns, nx.NumericsConfig(),
                                {"a": jnp.zeros(2)})
        with pytest.raises(ValueError):
            nx.numerics_observe(
                ns, nx.NumericsConfig(),
                {"a": jnp.zeros(2), "b": jnp.zeros(2)},
                weights={"c": jnp.zeros(2)})

    def test_scan_carryable(self):
        cfg = nx.NumericsConfig()
        ns = nx.numerics_init(cfg, sites=("t",))

        def body(ns, x):
            return nx.numerics_observe(ns, cfg, {"t": x}), x

        xs = jnp.ones((5, 3), jnp.float32)
        ns, _ = jax.lax.scan(body, ns, xs)
        assert int(ns.check_count) == 5


# --- verdicts -----------------------------------------------------------------

class TestPrecisionReport:
    def test_tiny_tensor_needs_scale(self):
        rng = np.random.RandomState(1)
        ns, sites = _observe_once({"t": _signed_pow2(rng, -12, -2)})
        rep = nx.precision_report(ns, sites)
        (r,) = rep.rows
        assert r.required_dtype == "fp8_e4m3"
        assert r.recommended_scale > 1
        assert r.by_format["fp8_e4m3"]["unscaled_underflow"] > 0.3
        assert r.predicted_underflow_frac <= rep.underflow_threshold

    def test_wide_range_needs_wider_format(self):
        rng = np.random.RandomState(2)
        # 36 octaves of dynamic range: no scale fits e4m3's 15-binade
        # normal span or e5m2/fp16's 30 — bf16 is the verdict
        ns, sites = _observe_once({"t": _signed_pow2(rng, -18, 18)})
        rep = nx.precision_report(ns, sites)
        (r,) = rep.rows
        assert r.required_dtype == "bf16"
        assert r.range_bits == pytest.approx(36, abs=1.5)

    def test_surprise_vs_ok(self):
        rng = np.random.RandomState(3)
        ns, sites = _observe_once({"t": _signed_pow2(rng, -18, 18)})
        rep = nx.precision_report(ns, sites,
                                  current_dtypes="float16")
        (r,) = rep.rows
        assert r.ok is False
        assert rep.surprises() == [r]
        gaps = rep.worst_gaps()
        assert gaps and gaps[0]["site"] == "t"
        assert gaps[0]["required_dtype"] == "bf16"
        rep2 = nx.precision_report(ns, sites,
                                   current_dtypes="bfloat16")
        assert rep2.rows[0].ok is True and not rep2.surprises()
        rep3 = nx.precision_report(ns, sites)
        assert rep3.rows[0].ok is None

    def test_ok_prices_current_format_unscaled(self):
        """The reviewed blind spot: a tensor living at ~2^-40 fits
        fp8_e4m3 WITH a scale (required_dtype narrower than fp16), but
        it runs at fp16 TODAY with no scale — where it wholly
        underflows. ok must price the current format unscaled, not
        compare ladder positions of scale-assisted verdicts."""
        rng = np.random.RandomState(9)
        ns, sites = _observe_once({"t": _signed_pow2(rng, -42, -38)})
        rep = nx.precision_report(ns, sites, current_dtypes="fp16")
        (r,) = rep.rows
        assert r.by_format["fp16"]["unscaled_underflow"] == 1.0
        assert r.required_dtype == "fp8_e4m3"    # narrower, WITH scale
        assert r.ok is False                     # but today: surprise
        assert rep.surprises() == [r]
        gaps = rep.worst_gaps()
        assert gaps and gaps[0]["underflow_frac"] == 1.0

    def test_check_events_unknown_dtype_refused(self):
        ns, sites = _observe_once({"t": jnp.ones((4,), jnp.float32)})
        with pytest.raises(ValueError):
            nx.check_events(ns, sites, current_dtype="bfloat_16")
        assert nx.check_events(ns, sites, current_dtype=None)

    def test_saturation_flagged(self):
        # 1e5 sits in the 2^16 binade — strictly above fp16's top
        # binade, so it counts as saturation unscaled (6e4 would NOT:
        # it shares 65504's binade and the half-bucket approximation
        # counts it representable — docs/numerics.md#formats)
        x = jnp.asarray([1e3, 1e5, 3e4], jnp.float32)
        ns, sites = _observe_once({"t": x})
        rep = nx.precision_report(ns, sites)
        (r,) = rep.rows
        assert r.by_format["fp16"]["unscaled_saturation"] == \
            pytest.approx(1 / 3)
        assert r.predicted_saturation_frac <= rep.saturation_threshold
        assert r.by_format["fp16"]["scale"] < 1

    def test_fp8_candidates_shape(self):
        rng = np.random.RandomState(4)
        ns, sites = _observe_once({"a": _signed_pow2(rng, -3, 3),
                                   "b": _signed_pow2(rng, -18, 18)})
        rep = nx.precision_report(ns, sites)
        cands = rep.fp8_candidates()
        assert [c["site"] for c in cands] == ["a"]
        assert set(cands[0]) >= {"fingerprint", "site",
                                 "required_dtype",
                                 "recommended_scale"}

    def test_stats_json_round_trip(self):
        rng = np.random.RandomState(5)
        ns, sites = _observe_once({"t": _signed_pow2(rng, -9, 2)})
        text = nx.stats_to_json(ns, sites)
        rep_a = nx.precision_report(ns, sites)
        rep_b = nx.precision_report(nx.stats_from_json(text))
        assert [(r.fingerprint, r.required_dtype, r.recommended_scale)
                for r in rep_a.rows] == \
               [(r.fingerprint, r.required_dtype, r.recommended_scale)
                for r in rep_b.rows]


class TestCommittedFixturePin:
    """``precision_report()`` on the committed BERT fixture is a pure
    host-side function of committed bytes — the verdict list is pinned
    in CI (the ISSUE-15 acceptance criterion)."""

    def _report(self):
        with open(FIXTURE) as f:
            return nx.precision_report(nx.stats_from_json(f.read()))

    def test_verdict_list_pinned(self):
        rep = self._report()
        canon = json.dumps([(r.fingerprint, r.required_dtype,
                             r.recommended_scale) for r in rep.rows])
        assert hashlib.sha256(canon.encode()).hexdigest() == \
            FIXTURE_VERDICT_DIGEST
        assert len(rep.rows) == 84
        # the measured BERT ranges are fp8-range-safe with scaling —
        # the ROADMAP item-5 rollout candidate list is non-empty
        assert all(r.required_dtype in ("fp8_e4m3", "fp8_e5m2")
                   for r in rep.rows)

    def test_deterministic_across_runs(self):
        a, b = self._report(), self._report()
        assert [r.to_event() for r in a.rows] == \
               [r.to_event() for r in b.rows]

    def test_no_surprises_at_current_formats(self):
        with open(FIXTURE) as f:
            stats = nx.stats_from_json(f.read())
        cur = {s: ("bf16" if s.startswith("amp/cast/") else "fp32")
               for s in stats["sites"]}
        rep = nx.precision_report(stats, current_dtypes=cur)
        assert rep.surprises() == []


# --- ScaleHistory -------------------------------------------------------------

class TestScaleHistory:
    def test_init_validation(self):
        with pytest.raises(ValueError):
            amp.scale_history_init(
                amp.ScaleHistoryConfig(fmt="fp12"), n_sites=1)
        with pytest.raises(ValueError):
            amp.scale_history_init(
                amp.ScaleHistoryConfig(window=0), n_sites=1)
        with pytest.raises(ValueError):
            amp.scale_history_init(amp.ScaleHistoryConfig(), n_sites=0)
        # non-pow2 factors would break the exact-exponent-shift
        # invariant on the first backoff — refused at init
        with pytest.raises(ValueError):
            amp.scale_history_init(
                amp.ScaleHistoryConfig(backoff_factor=0.3), n_sites=1)
        with pytest.raises(ValueError):
            amp.scale_history_init(
                amp.ScaleHistoryConfig(growth_factor=3.0), n_sites=1)

    def test_scales_are_exact_powers_of_two(self):
        cfg = amp.ScaleHistoryConfig(window=2, growth_factor=2.0 ** 40)
        sh = amp.scale_history_init(cfg, n_sites=1)
        for a in (3.7e-5, 11.0, 0.9):
            sh = amp.scale_history_update(sh, cfg,
                                          jnp.asarray([a], jnp.float32))
            s = float(sh.scale[0])
            m, _e = np.frexp(np.float32(s))
            assert m == 0.5, s                   # exact power of two

    def test_delayed_scaling_formula(self):
        cfg = amp.ScaleHistoryConfig(window=4, margin=2.0,
                                     growth_factor=2.0 ** 40)
        sh = amp.scale_history_init(cfg, n_sites=1)
        sh = amp.scale_history_update(sh, cfg, jnp.asarray([2.0 ** -8]))
        # 448 / (2 * 2^-8) = 57344 -> 2^15
        assert float(sh.scale[0]) == 2.0 ** 15

    def test_shrink_immediate_growth_rate_limited(self):
        cfg = amp.ScaleHistoryConfig(window=1, growth_factor=2.0,
                                     growth_interval=2)
        sh = amp.scale_history_init(cfg, n_sites=1)
        # big amax: target far below 1.0 — shrink applies IMMEDIATELY
        sh = amp.scale_history_update(sh, cfg, jnp.asarray([1e6]))
        assert float(sh.scale[0]) < 1.0
        low = float(sh.scale[0])
        # tiny amax: the window-derived target leaps to 2^13, but the
        # tracker (1 prior clean update + this one = interval) gates a
        # single RATE-LIMITED x2 hop, not the leap — then resets, so
        # the next update holds, then hops again
        sh = amp.scale_history_update(sh, cfg, jnp.asarray([2.0 ** -6]))
        assert float(sh.scale[0]) == low * 2     # one x2 hop, not 2^13
        sh = amp.scale_history_update(sh, cfg, jnp.asarray([2.0 ** -6]))
        assert float(sh.scale[0]) == low * 2     # tracker reset: hold
        sh = amp.scale_history_update(sh, cfg, jnp.asarray([2.0 ** -6]))
        assert float(sh.scale[0]) == low * 4     # next gated hop

    def test_backoff_on_nonfinite_and_window_hygiene(self):
        cfg = amp.ScaleHistoryConfig(window=4)
        sh = amp.scale_history_init(cfg, n_sites=2)
        # site 1's amax of 224 pins its target at exactly 1.0
        # (448 / (2 * 224)) — a stationary control row
        sh = amp.scale_history_update(sh, cfg,
                                      jnp.asarray([1.0, 224.0]))
        before = np.asarray(sh.scale)
        sh = amp.scale_history_update(sh, cfg,
                                      jnp.asarray([np.inf, 224.0]))
        after = np.asarray(sh.scale)
        assert after[0] == before[0] * cfg.backoff_factor
        assert after[1] == before[1] == 1.0
        assert int(sh.overflow_count[0]) == 1
        assert int(sh.overflow_count[1]) == 0
        # the poisoned measurement never entered the history
        assert np.isfinite(np.asarray(sh.amax_history)).all()

    def test_scale_amax_carries_overflow_signal(self):
        """The reviewed hole: NumericsState.amax is the FINITE max by
        design (EMAs/verdicts stay usable through an overflow), so it
        alone can never trigger the backoff — scale_amax substitutes
        inf wherever the fold saw nonfinite elements, and THAT feed
        backs the scale off instead of letting the poisoned step's
        finite remainder grow it."""
        x = jnp.asarray([3.0, np.inf, 1.0], jnp.float32)
        ns, _ = _observe_once({"t": x})
        assert float(ns.amax[0]) == 3.0      # finite max, by design
        sa = np.asarray(nx.scale_amax(ns))
        assert np.isinf(sa[0])
        assert np.isinf(np.asarray(nx.scale_amax(ns, [0])))[0]
        cfg = amp.ScaleHistoryConfig(window=2)
        sh = amp.scale_history_init(cfg, n_sites=1)
        sh = amp.scale_history_update(sh, cfg, nx.scale_amax(ns))
        assert int(sh.overflow_count[0]) == 1
        assert float(sh.scale[0]) == cfg.backoff_factor
        # a clean observation routes the true amax through unchanged
        ns2, _ = _observe_once({"t": jnp.asarray([3.0, 1.0])})
        assert float(nx.scale_amax(ns2)[0]) == 3.0

    def test_events_actions(self):
        cfg = amp.ScaleHistoryConfig(window=1, growth_factor=2.0 ** 40)
        sh = amp.scale_history_init(cfg, n_sites=1)
        prev, sh = sh, amp.scale_history_update(
            sh, cfg, jnp.asarray([2.0 ** -8]))
        (ev,) = amp.scale_update_events(prev, sh, ("s",))
        assert ev["kind"] == "scale_update" and ev["action"] == "grow"
        prev, sh = sh, amp.scale_history_update(
            sh, cfg, jnp.asarray([1e5]))
        (ev,) = amp.scale_update_events(prev, sh, ("s",))
        assert ev["action"] == "shrink"
        prev, sh = sh, amp.scale_history_update(
            sh, cfg, jnp.asarray([np.inf]))
        (ev,) = amp.scale_update_events(prev, sh, ("s",))
        # the window records the previous max on an overflow step (the
        # poisoned measurement never enters the history), so the event
        # gauge is that recorded — finite — value
        assert ev["action"] == "backoff" and ev["amax"] == 1e5
        prev, sh = sh, amp.scale_history_update(
            sh, cfg, jnp.asarray([np.inf]))
        assert amp.scale_update_events(prev, sh, ("s",),
                                       include_holds=False)
        evs = amp.scale_update_events(prev, prev, ("s",),
                                      include_holds=True)
        assert evs and evs[0]["action"] == "hold"


# --- the amp hook + opt-level parity sweep ------------------------------------

class TestAmpHook:
    def _run(self, opt_level, observe, steps=5):
        import optax
        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(16, 4).astype("float32")
                                   * 0.1),
                  "b": jnp.zeros((4,), jnp.float32)}
        x = jnp.asarray(rng.randn(8, 16).astype("float32"))
        y = jnp.asarray(rng.randn(8, 4).astype("float32"))
        amp_opt, state = amp.initialize(params, optax.sgd(0.05),
                                        opt_level, verbosity=0)

        def loss_fn(mp, x, y):
            return jnp.mean(jnp.square(x @ mp["w"] + mp["b"] - y))

        ncfg = nx.NumericsConfig(check_every=2)
        ns = nx.numerics_init(
            ncfg, sites=amp_opt.numerics_sites(state.params))

        if observe:
            @jax.jit
            def step(state, ns, x, y):
                state, loss, fin, ns = amp_opt.step(
                    state, loss_fn, x, y, numerics=(ns, ncfg))
                return state, ns, loss
        else:
            @jax.jit
            def step(state, ns, x, y):
                state, loss, fin = amp_opt.step(state, loss_fn, x, y)
                return state, ns, loss

        losses = []
        for _ in range(steps):
            state, ns, loss = step(state, ns, x, y)
            losses.append(np.asarray(loss).tobytes())
        return losses, jax.device_get(state.params), ns

    @pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
    def test_trajectory_bit_identical_observed_vs_not(self, opt_level):
        """The zero-dispatch claim enforced at the TRAJECTORY level:
        every opt level's losses and params are bitwise identical with
        the numerics fold on vs off — observation reads, never
        feeds back."""
        l_obs, p_obs, ns = self._run(opt_level, observe=True)
        l_ref, p_ref, _ = self._run(opt_level, observe=False)
        assert l_obs == l_ref
        for k in p_ref:
            assert np.array_equal(np.asarray(p_obs[k]),
                                  np.asarray(p_ref[k])), (opt_level, k)
        assert int(ns.check_count) == 3          # steps 0, 2, 4

    def test_numerics_sites_naming(self):
        import optax
        params = {"w": jnp.ones((2, 2))}
        amp_opt, _ = amp.initialize(params, optax.sgd(0.1), "O2",
                                    verbosity=0)
        sites = amp_opt.numerics_sites(params)
        assert sites == ("amp/cast/['w']", "amp/grads/['w']",
                         "amp/update/['w']")

    def test_step_returns_grow_with_guard(self):
        import optax
        from apex_tpu import guard
        params = {"w": jnp.ones((4, 2), jnp.float32)}
        gcfg = guard.GuardConfig(window=8, min_history=2)
        amp_opt, state = amp.initialize(params, optax.sgd(0.1), "O2",
                                        verbosity=0)
        ncfg = nx.NumericsConfig()
        ns = nx.numerics_init(ncfg,
                              sites=amp_opt.numerics_sites(params))

        def lf(mp):
            return jnp.mean(jnp.square(mp["w"]))

        ret = amp_opt.step(state, lf, numerics=(ns, ncfg))
        assert len(ret) == 4 and isinstance(ret[3], nx.NumericsState)
        gs = guard.guard_init(gcfg)
        ret = amp_opt.step(state, lf, guard=(gs, gcfg),
                           numerics=(ns, ncfg))
        assert len(ret) == 5 and isinstance(ret[4], nx.NumericsState)
        # update-to-weight folded for the committed delta
        rep = nx.precision_report(ret[4],
                                  amp_opt.numerics_sites(params))
        uw = {r.site: r.uw_ratio for r in rep.rows}
        assert uw["amp/update/['w']"] is not None

    def test_guard_lr_backoff_does_not_skew_grad_telemetry(self):
        """The amp/grads site observes the UNSCALED fp32 grads: the
        guard's lr_scale damping is a response, not a property of the
        gradients — telemetry must read the same with or without a
        guard threaded (a 0.5 backoff would otherwise shift every
        grad site's measured range by a binade)."""
        import optax
        from apex_tpu import guard
        params = {"w": jnp.full((4, 2), 2.0, jnp.float32)}
        gcfg = guard.GuardConfig(window=8, min_history=2)
        amp_opt, state = amp.initialize(params, optax.sgd(0.1), "O2",
                                        verbosity=0)
        sites = amp_opt.numerics_sites(params)
        ncfg = nx.NumericsConfig()

        def lf(mp):
            return jnp.mean(jnp.square(mp["w"]))

        ns0 = nx.numerics_init(ncfg, sites=sites)
        gs = guard.guard_init(gcfg)._replace(
            lr_scale=jnp.float32(0.25))
        *_, ns_guarded = amp_opt.step(state, lf, guard=(gs, gcfg),
                                      numerics=(ns0, ncfg))
        *_, ns_plain = amp_opt.step(state, lf, numerics=(ns0, ncfg))
        gi = sites.index("amp/grads/['w']")
        assert float(ns_guarded.amax[gi]) == float(ns_plain.amax[gi])


# --- the advisor (roofline what-if join) --------------------------------------

class TestAdvisor:
    def _roofline(self):
        from apex_tpu.prof.roofline import RooflineReport, RooflineRow

        def row(name, scope, dtype, flops, nbytes, measured,
                peak=1e12, bw=1e11):
            compute = flops / peak * 1e6
            memory = nbytes / bw * 1e6
            return RooflineRow(
                name=name, opcode="dot", family="gemm", scope=scope,
                flops=flops, bytes=nbytes, occurrences=1,
                measured_us=measured, compute_us=compute,
                memory_us=memory,
                bound="compute" if compute >= memory else "memory",
                dtype=dtype, shape=f"{dtype}[128,128]")

        rows = [row("dot.1", "encoder/mlp/dense", "bf16",
                    flops=2e9, nbytes=1e6, measured=2500.0),
                row("dot.2", "encoder/attn/qk", "f32",
                    flops=1e8, nbytes=8e6, measured=100.0)]
        return RooflineReport(rows=rows, device_kind="test",
                              peak_flops=1e12, hbm_bw=1e11,
                              profile_total_us=0.0,
                              module_total_us=0.0, module_runs=0)

    def test_what_if_column(self):
        rep = self._roofline()
        out = rep.what_if({"mlp/dense": "fp8_e4m3"})
        (w,) = out
        assert w["dtype_from"] == "bf16" and w["dtype_to"] == "fp8_e4m3"
        # halving the element width halves both bounds in this model
        assert w["whatif_attainable_us"] == pytest.approx(
            w["attainable_us"] / 2, rel=1e-3)
        assert w["whatif_gain_us"] > 0
        # a target not narrower than the current dtype yields no row
        assert rep.what_if({"mlp/dense": "bf16"}) == []
        with pytest.raises(ValueError):
            rep.what_if({"mlp/dense": "fp13"})

    def test_advisor_ranks_by_gain_times_safety(self):
        rng = np.random.RandomState(6)
        ns, sites = _observe_once({
            "mlp/dense": _signed_pow2(rng, -3, 3),
            "attn/qk": _signed_pow2(rng, -3, 3)})
        verdicts = nx.precision_report(ns, sites)
        ranked = nx.placement_advisor(self._roofline(), verdicts)
        assert ranked
        # the mlp row has the larger what-if gain — it ranks first
        assert ranked[0]["site"] == "mlp/dense"
        assert ranked[0]["rank_score"] >= ranked[-1]["rank_score"]
        assert set(ranked[0]) >= {"required_dtype",
                                  "recommended_scale",
                                  "numeric_safety",
                                  "verdict_fingerprint"}

    def test_advisor_skips_unsafe_sites(self):
        rng = np.random.RandomState(7)
        ns, sites = _observe_once({
            "mlp/dense": _signed_pow2(rng, -18, 18)})   # needs bf16
        verdicts = nx.precision_report(ns, sites,
                                       current_dtypes="float16")
        # the site is a surprise at fp16 — never a placement candidate
        assert nx.placement_advisor(self._roofline(), verdicts) == []


# --- the numerics channel + schema --------------------------------------------

def _lines(events):
    return [json.dumps(e) for e in events]


_CHECK_EV = {"kind": "numerics_check", "rank": 0, "step": 4,
             "check_count": 2, "site": "grads/['w']", "n_sites": 3,
             "amax": 1.5, "amin": 1e-6, "underflow_frac": 0.01,
             "overflow_frac": 0.0, "zero_frac": 0.25,
             "nonfinite_frac": 0.0, "uw_ratio": 0.001}
_SCALE_EV = {"kind": "scale_update", "rank": 0, "step": 4,
             "site": "grads/['w']", "action": "grow", "scale": 256.0,
             "prev_scale": 128.0, "amax": 0.5}
_VERDICT_EV = {"kind": "precision_verdict", "rank": 0, "step": None,
               "site": "grads/['w']", "site_kind": "grads",
               "required_dtype": "fp8_e4m3", "current_dtype": "fp32",
               "predicted_underflow_frac": 0.0,
               "predicted_saturation_frac": 0.0,
               "recommended_scale": 256.0, "amax": 0.5, "ok": True,
               "fingerprint": "numerics|grads|grads/['w']"}


class TestNumericsSchema:
    def _check(self, lines):
        from scripts.check_metrics_schema import check_numerics_lines
        return check_numerics_lines(lines)

    def test_valid_stream(self):
        assert self._check(_lines([_CHECK_EV, _SCALE_EV,
                                   _VERDICT_EV])) == []

    def test_aggregate_row_nullable_site(self):
        ev = dict(_CHECK_EV, site=None, amax=None, amin=None,
                  underflow_frac=None, overflow_frac=None,
                  uw_ratio=None)
        assert self._check(_lines([ev])) == []

    def test_unknown_kind_rejected(self):
        errs = self._check(_lines([dict(_CHECK_EV,
                                        kind="numerics_meow")]))
        assert errs and "kind" in errs[0]

    def test_missing_required_key_rejected(self):
        ev = dict(_VERDICT_EV)
        del ev["fingerprint"]
        assert any("fingerprint" in e
                   for e in self._check(_lines([ev])))

    def test_fraction_out_of_range_rejected(self):
        assert self._check(_lines([dict(_CHECK_EV,
                                        underflow_frac=1.5)]))
        assert self._check(_lines([dict(
            _VERDICT_EV, predicted_saturation_frac=-0.1)]))

    def test_bad_action_rejected(self):
        assert self._check(_lines([dict(_SCALE_EV, action="explode")]))

    def test_bad_format_rejected(self):
        assert self._check(_lines([dict(_VERDICT_EV,
                                        required_dtype="fp12")]))
        assert self._check(_lines([dict(_VERDICT_EV,
                                        current_dtype="int8")]))

    def test_nonpositive_scale_rejected(self):
        assert self._check(_lines([dict(_SCALE_EV, scale=0.0)]))
        assert self._check(_lines([dict(_VERDICT_EV,
                                        recommended_scale=-2.0)]))

    def test_null_site_on_scale_update_rejected(self):
        assert self._check(_lines([dict(_SCALE_EV, site=None)]))

    def test_nonfinite_number_rejected(self):
        line = json.dumps(dict(_CHECK_EV, amax=1.0)) \
            .replace("1.0", "Infinity")
        assert self._check([line])

    def test_nonbool_ok_rejected(self):
        assert self._check(_lines([dict(_VERDICT_EV, ok="yes")]))

    def test_library_emission_validates(self):
        rng = np.random.RandomState(8)
        ns, sites = _observe_once({"grads": {
            "w": _signed_pow2(rng, -6, 2, n=64)}})
        evs = nx.check_events(ns, sites, current_dtype="bfloat16")
        evs += nx.precision_report(
            ns, sites, current_dtypes="float32").to_events()
        assert self._check(_lines(evs)) == []

    def test_logger_channel_round_trip(self, tmp_path):
        from apex_tpu import monitor
        out = tmp_path / "numerics.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], numerics_sink=monitor.JSONLSink(str(out)))
        logger.record_numerics(dict(_CHECK_EV, amax=float("nan")))
        logger.close()
        with open(out) as f:
            rec = json.loads(f.read())
        assert rec["amax"] is None               # non-finite nulled
        with open(out) as f:
            assert self._check(f) == []


class TestChannelRegistry:
    """The MetricsLogger registry refactor: every channel is one
    declarative row; numerics is the 10th, podview the 11th,
    sharding the 12th, dynamics the 13th."""

    def test_thirteen_channels_dynamics_last(self):
        from apex_tpu import monitor
        names = [c.name for c in monitor.CHANNELS]
        assert len(names) == 13 and names[-1] == "dynamics"
        assert names[-2] == "sharding"

    def test_registry_kinds_match_schema_registry(self):
        from apex_tpu import monitor
        from scripts.check_metrics_schema import SCHEMAS
        for spec in monitor.CHANNELS:
            assert tuple(SCHEMAS[spec.name].kinds) == tuple(spec.kinds)

    def test_unknown_sink_kwarg_refused(self):
        from apex_tpu import monitor
        with pytest.raises(TypeError):
            monitor.MetricsLogger(sinks=[], bogus_sink=None)

    def test_every_record_method_exists(self):
        from apex_tpu import monitor
        logger = monitor.MetricsLogger(sinks=[])
        for spec in monitor.CHANNELS:
            assert callable(getattr(logger, spec.method))
        logger.close()


class TestCompileCheck:
    def test_numerics_case_runs_green(self):
        from apex_tpu.ops import compile_check as cc
        assert cc.run(pattern="numerics")
