#!/usr/bin/env python
"""apexlint CLI — lint compiled training steps before they cost a run.

Three ways to name the step:

``--flagship resnet|bert|both|guarded|ckpt|dynamics|all`` (default: both)
    The BASELINE.md flagship steps, built exactly as ``bench.py`` runs
    them (ResNet-50 amp O2 + FusedSGD; BERT LAMB amp O1), jitted WITH
    their donation so the donation rule audits the real program. On an
    accelerator the full-size configs are used; on CPU the structural
    downscalings (the same convention as ``pod_comm_budget --cpu8`` /
    ``memory_budget --cpu8``: ResNet at 64px/b8, a 4-layer BERT at
    seq 128) — same step structure, CPU-compilable. ``guarded``,
    ``ckpt`` and ``dynamics`` are the self-audit targets: the
    guard-instrumented flagship step (``Amp.step(guard=)``), the
    checkpoint snapshot copy program, and the training-dynamics
    instrumented step (``Amp.step(dynamics=)``) — instrumentation that
    landed after the linter did and must stay clean; ``all`` = all
    five.

``--opt-level O0|O1|O2|O3|all``
    Rebuild the resnet/bert flagships at that amp opt level (instead
    of their measured O2/O1 configurations) and lint each — the
    precision pass (APX3xx, docs/linting.md#apx3xx) must certify the
    amp machinery at EVERY level; ``all`` sweeps all four.
    ``run_tier1.sh --smoke`` runs ``--opt-level all --fail-on error``
    as the mixed-precision certification gate. Targets without an opt
    level (guarded/ckpt/dynamics/--import/--hlo) are built as usual.

``--precision-stats FILE``
    A committed numerics stats fixture (``stats_to_json`` output, e.g.
    ``tests/fixtures/bert_numerics_stats.json``). Activates APX306 —
    collective wire dtypes joined against the fixture's measured
    per-site ``precision_report`` verdicts — and prints the
    ``precision_preflight`` table: every measured fp8-safe site,
    ranked, flagged castable only when the program has no static
    APX3xx errors (the fp8/O4 pre-flight).

``--import pkg.mod:builder``
    ``builder()`` must return ``(step_fn, args)`` or
    ``(step_fn, args, policy)``; ``step_fn`` may be jitted (pass your
    real ``donate_argnums``).

``--hlo FILE``
    HLO-pass-only lint of a dumped optimized-HLO text file
    (``scripts/dump_hlo.py`` output or an XLA dump).

``--mesh dp2x4|2slice|iciN|model.json`` switches on the cross-rank
SPMD pass (APX201 congruence/deadlock, APX202 implicit full gather,
APX203 DCN-crossing flat collective — docs/linting.md#apx2xx): the
flagship targets become their DDP shard_map variants compiled over a
matching device mesh (on CPU: 8 virtual devices, structural
downscalings per the ``pod_comm_budget --cpu8`` convention), with the
topology judged against the declarative mesh model
(``apex_tpu.lint.mesh_model``). A MULTI-SLICE model builds the
factored mesh and the hierarchical ``comm_plan`` flagship
(docs/parallel.md#hierarchical) — APX203-clean is the expected state
(docs/linting.md#apx203-clean). ``--flat-sync`` forces the historical
flat-mesh flat-sync variant instead: the negative-twin/debug view
whose APX203 finding carries the model's (possibly measured) DCN hop
milliseconds — ``goodput_audit --cpu8`` uses it to prove measured
bytes/s reach the evidence. With ``--hlo``/``--import`` the mesh
model applies to those modules instead. ``run_tier1.sh --smoke`` runs
``--mesh dp2x4 --fail-on error`` as the cpu8 cross-rank congruence
audit: the flagships must report zero errors and no APX203.

Output: the finding table on stdout; ``--jsonl FILE`` streams
``lint_report``/``lint_finding`` events through the
``MetricsLogger(lint_sink=...)`` channel (validate with
``check_metrics_schema.py --kind lint``); ``--json`` prints a summary
object. ``--baseline FILE`` suppresses previously-accepted findings
(``--write-baseline`` records the current findings as that file);
``--fail-on error|warning|never`` (default error) sets the exit gate —
``run_tier1.sh --smoke`` runs the flagship lint with the committed
(empty) ``scripts/apexlint_baseline.json`` so any new error-severity
finding breaks CI. Everything is AOT: trace + compile, zero dispatches.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))


def _build_flagship_resnet(opt_level="O2"):
    """The headline ResNet-50 amp O2 step, donated as bench measures
    it; ``opt_level`` rebuilds the same structure at another amp level
    (the ``--opt-level`` precision-certification sweep)."""
    import jax
    import bench
    from apex_tpu import amp
    on_tpu = jax.default_backend() == "tpu"
    batch, size = (256, 224) if on_tpu else (8, 64)
    step, (state, batch_stats), (x, y) = bench._resnet_step_builder(
        batch, size, opt_level)
    jstep = jax.jit(step, donate_argnums=(0, 1))
    return (jstep, (state, batch_stats, x, y),
            amp.Policy.from_opt_level(opt_level),
            f"resnet50_{opt_level.lower()}_step")


def _build_flagship_bert(opt_level="O1"):
    """The BERT LAMB step, built by bench's own `_bert_step_builder`
    (the lint gate audits the program the bench measures), donated. CPU
    uses a 4-layer structural downscale — XLA:CPU takes minutes just to
    compile the 24-layer BertLarge module (see bench._bert_row).
    ``opt_level`` rebuilds at another amp level for the sweep."""
    import jax
    import bench
    from apex_tpu import models

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        enc, batch, seq = None, 16, 512      # None -> full BertLarge
    else:
        enc = models.BertEncoder(30000, hidden=256, layers=4, heads=4,
                                 max_len=128)
        batch, seq = 2, 128
    step, state, (toks, labels), policy, _enc, _vars = \
        bench._bert_step_builder(batch, seq, encoder=enc,
                                 opt_level=opt_level)
    jstep = jax.jit(step, donate_argnums=(0,))
    return (jstep, (state, toks, labels), policy,
            f"bert_lamb_{opt_level.lower()}_step"
            if opt_level != "O1" else "bert_lamb_step")


def _build_flagship_guarded():
    """The guard-instrumented flagship step (self-audit: ``guard/``
    landed after the linter did — ``Amp.step(guard=)`` threads the
    anomaly detectors through the same resnet O2 program). Structural
    downscale on CPU, like the other flagships."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp, guard, models, ops
    from apex_tpu.optim import FusedSGD

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model = models.ResNet(stage_sizes=[3, 4, 6, 3],
                              num_classes=1000, dtype=jnp.bfloat16)
        batch, size = 256, 224
    else:
        model = models.ResNet(stage_sizes=[1, 1], num_classes=10,
                              width=16, dtype=jnp.bfloat16)
        batch, size = 8, 32
    policy = amp.Policy.from_opt_level("O2")
    amp_opt = amp.Amp(policy, FusedSGD(lr=0.1, momentum=0.9))
    x = jnp.zeros((batch, size, size, 3), jnp.float32)
    y = jnp.zeros((batch,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    state = amp_opt.init(variables["params"])
    batch_stats = variables["batch_stats"]
    cfg = guard.GuardConfig()
    gs = guard.guard_init(cfg)

    def step(state, gs, batch_stats, x, y):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, x,
                train=True, mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, y))
            return loss, mut["batch_stats"]

        state, (loss, new_bs), committed, gs = amp_opt.step(
            state, loss_fn, has_aux=True, guard=(gs, cfg))
        return state, gs, new_bs, loss

    jstep = jax.jit(step, donate_argnums=(0, 1, 2))
    return (jstep, (state, gs, batch_stats, x, y), policy,
            "guarded_resnet_o2_step")


def _build_flagship_dynamics():
    """The training-dynamics instrumented flagship step (self-audit:
    ``monitor/dynamics`` landed after the linter did —
    ``Amp.step(dynamics=)`` threads the GNS/geometry probes through the
    same resnet O2 program and must stay clean, 0 errors on the empty
    baseline, like ``guarded``/``ckpt``). Structural downscale on
    CPU."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp, models, ops
    from apex_tpu.monitor import dynamics as dx
    from apex_tpu.optim import FusedSGD

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        model = models.ResNet(stage_sizes=[3, 4, 6, 3],
                              num_classes=1000, dtype=jnp.bfloat16)
        batch, size = 256, 224
    else:
        model = models.ResNet(stage_sizes=[1, 1], num_classes=10,
                              width=16, dtype=jnp.bfloat16)
        batch, size = 8, 32
    policy = amp.Policy.from_opt_level("O2")
    amp_opt = amp.Amp(policy, FusedSGD(lr=0.1, momentum=0.9))
    x = jnp.zeros((batch, size, size, 3), jnp.float32)
    y = jnp.zeros((batch,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    state = amp_opt.init(variables["params"])
    batch_stats = variables["batch_stats"]
    dcfg = dx.DynamicsConfig(check_every=2, local_batch=batch)
    ds = dx.dynamics_init(dcfg,
                          sites=amp_opt.dynamics_sites(state.params))

    def step(state, ds, batch_stats, x, y):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, x,
                train=True, mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, y))
            return loss, mut["batch_stats"]

        state, (loss, new_bs), committed, ds = amp_opt.step(
            state, loss_fn, has_aux=True, dynamics=(ds, dcfg))
        return state, ds, new_bs, loss

    jstep = jax.jit(step, donate_argnums=(0, 1, 2))
    return (jstep, (state, ds, batch_stats, x, y), policy,
            "dynamics_resnet_o2_step")


def _build_flagship_ckpt():
    """The checkpoint snapshot's batched copy program over the flagship
    carried state (self-audit: ``ckpt/`` landed after the linter did).
    The copy program must NOT donate — fresh buffers are its donation
    safety — and must compile zero host traffic; this target pins
    both."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp, models
    from apex_tpu.ckpt.snapshot import _copy_leaves
    from apex_tpu.optim import FusedSGD

    on_tpu = jax.default_backend() == "tpu"
    model = (models.ResNet(stage_sizes=[3, 4, 6, 3], num_classes=1000,
                           dtype=jnp.bfloat16) if on_tpu else
             models.ResNet(stage_sizes=[1, 1], num_classes=10,
                           width=16, dtype=jnp.bfloat16))
    size = 224 if on_tpu else 32
    amp_opt = amp.Amp(amp.Policy.from_opt_level("O2"),
                      FusedSGD(lr=0.1, momentum=0.9))
    x = jnp.zeros((1, size, size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    state = amp_opt.init(variables["params"])
    leaves = [l for l in jax.tree_util.tree_leaves(
        (state, variables["batch_stats"]))
        if isinstance(l, jax.Array)]
    return (_copy_leaves, (leaves,), None, "ckpt_copy_leaves")


FLAGSHIPS = {"resnet": _build_flagship_resnet,
             "bert": _build_flagship_bert,
             "guarded": _build_flagship_guarded,
             "ckpt": _build_flagship_ckpt,
             "dynamics": _build_flagship_dynamics}
#: flagships whose builder takes an amp opt level (the --opt-level
#: sweep subjects; the self-audit targets are fixed-config)
OPT_LEVEL_FLAGSHIPS = frozenset({"resnet", "bert"})
OPT_LEVELS = ("O0", "O1", "O2", "O3")
#: --flagship group aliases ("both" predates guarded/ckpt and keeps
#: its original meaning)
FLAGSHIP_GROUPS = {"both": ("resnet", "bert"),
                   "all": ("resnet", "bert", "guarded", "ckpt",
                           "dynamics")}


def _mesh_comm_plan(mesh_model, grad_bytes):
    """The hierarchical ``CommPlan`` for a multi-slice mesh model (the
    collectives-v2 flagship path: APX203-clean by construction), or
    None for a single-slice model (the flat path stays the subject)."""
    from apex_tpu.parallel import hierarchy

    if not any(a.link == "dcn" for a in mesh_model.axes):
        return None
    return hierarchy.plan_comm(mesh_model, grad_bytes=grad_bytes)


def _build_mesh_flagship_resnet(mesh, mesh_model=None):
    """The flagship O2+DDP step over a device mesh — the exact
    ``pod_comm_budget.build_step`` program (shared definition), at the
    ``--cpu8`` structural scale off-TPU, jitted with donated carried
    state. Linted with a mesh model this is the cross-rank congruence
    audit target; a MULTI-SLICE model makes it the hierarchical
    compressed-sync flagship (``comm_plan`` from the model — APX203 is
    expected ABSENT; the flat negative twin lives in
    ``pod_comm_budget --cpu8`` and tests/test_pod_hlo.py)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import pod_comm_budget as pcb
    from apex_tpu import amp, models

    on_tpu = jax.default_backend() == "tpu"
    n = int(np.prod(mesh.devices.shape))
    if on_tpu:
        model, size, per_chip = None, 224, 256
    else:
        model = models.ResNet(stage_sizes=[1, 1], num_classes=10,
                              width=16, dtype=jnp.bfloat16)
        size, per_chip = 32, 4
    if model is None:
        model = models.ResNet(stage_sizes=[3, 4, 6, 3],
                              num_classes=1000, dtype=jnp.bfloat16)
    x1 = jnp.ones((2, size, size, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x1, train=True))
    n_params = sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(variables["params"]))
    plan = (None if mesh_model is None
            else _mesh_comm_plan(mesh_model, 4 * n_params))
    step, model, amp_opt, ddp = pcb.build_step(mesh, False, model=model,
                                               comm_plan=plan)
    state_s = jax.eval_shape(
        lambda: amp_opt.init(jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, a.dtype),
            variables["params"])))
    x_s = jax.ShapeDtypeStruct((per_chip * n, size, size, 3),
                               jnp.float32)
    y_s = jax.ShapeDtypeStruct((per_chip * n,), jnp.int32)
    stepped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(ddp.axis_name), P(ddp.axis_name)),
        out_specs=(P(), P(), P()), check_vma=False),
        donate_argnums=(0, 1))
    name = ("resnet50_o2_hier_ddp_step" if plan is not None
            else "resnet50_o2_ddp_step")
    return (stepped,
            (state_s, variables["batch_stats"], x_s, y_s),
            amp.Policy.from_opt_level("O2"), name)


def _build_mesh_flagship_bert(mesh, mesh_model=None):
    """The BERT-LAMB step DDP-wrapped over a device mesh (grad
    all-reduce under the ``ddp/sync_gradients`` span), donated. A
    multi-slice mesh model selects the hierarchical ``comm_plan`` like
    the resnet sibling."""
    import jax
    from jax.sharding import PartitionSpec as P

    import bench
    from apex_tpu import models, parallel

    on_tpu = jax.default_backend() == "tpu"
    n = int(np.prod(mesh.devices.shape))
    if on_tpu:
        enc, per_chip, seq = None, 16, 512
    else:
        enc = models.BertEncoder(30000, hidden=128, layers=2, heads=2,
                                 max_len=64)
        per_chip, seq = 1, 64
    plan = None
    if mesh_model is not None:
        import jax.numpy as jnp
        e = enc if enc is not None else models.BertLarge()
        toks_s = jnp.zeros((1, seq), jnp.int32)
        var_s = jax.eval_shape(
            lambda: e.init(jax.random.PRNGKey(0), toks_s))
        n_params = sum(int(np.prod(l.shape)) for l in
                       jax.tree_util.tree_leaves(var_s["params"]))
        plan = _mesh_comm_plan(mesh_model, 4 * n_params)
    ddp = parallel.DistributedDataParallel(mesh, comm_plan=plan)
    step, state, (toks, labels), policy, _enc, _vars = \
        bench._bert_step_builder(per_chip * n, seq, encoder=enc,
                                 ddp=ddp)
    stepped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(ddp.axis_name), P(ddp.axis_name)),
        out_specs=(P(), P()), check_vma=False), donate_argnums=(0,))
    name = ("bert_lamb_hier_ddp_step" if plan is not None
            else "bert_lamb_ddp_step")
    return (stepped, (state, toks, labels), policy, name)


MESH_FLAGSHIPS = {"resnet": _build_mesh_flagship_resnet,
                  "bert": _build_mesh_flagship_bert}


def _import_builder(spec):
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise SystemExit(f"--import wants pkg.mod:builder, got {spec!r}")
    import importlib
    built = getattr(importlib.import_module(mod_name), fn_name)()
    if len(built) == 2:
        fn, args = built
        policy = None
    else:
        fn, args, policy = built[:3]
    return fn, args, policy, spec


def _mesh_for_model(mm, flat_sync=False):
    """A device mesh matching the mesh model: factored by the model's
    own axes (row-major, the same layout the model's coordinate
    arithmetic assumes), so a multi-slice model yields the factored
    mesh the hierarchical ``comm_plan`` runs on — the program axes ARE
    the physical axes. ``flat_sync`` keeps the historical flat
    single-``data``-axis view (the flat DDP sync over a multi-slice
    model is then exactly what APX203 exists to call out)."""
    import jax
    from jax.sharding import Mesh

    from apex_tpu import parallel

    devs = jax.devices()
    if len(devs) < mm.n_devices:
        raise SystemExit(f"mesh model {mm!r} needs {mm.n_devices} "
                         f"devices, have {len(devs)}")
    if flat_sync or len(mm.axes) == 1:
        return Mesh(np.array(devs[:mm.n_devices]),
                    (parallel.DATA_AXIS,))
    sizes = [a.size for a in mm.axes]
    return Mesh(np.array(devs[:mm.n_devices]).reshape(sizes),
                mm.axis_names)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    flagship = None
    imports, hlo_files = [], []
    baseline_path = write_baseline = jsonl_path = mesh_spec = None
    opt_level = precision_stats = None
    fail_on = "error"
    as_json = flat_sync = False
    it = iter(argv)
    for a in it:
        if a in ("-h", "--help"):
            print(__doc__)
            return 2
        elif a == "--json":
            as_json = True
            continue
        elif a == "--flat-sync":
            flat_sync = True
            continue
        elif a not in ("--flagship", "--import", "--hlo", "--baseline",
                       "--write-baseline", "--jsonl", "--fail-on",
                       "--mesh", "--opt-level", "--precision-stats"):
            print(f"unknown arg {a!r}\n{__doc__}", file=sys.stderr)
            return 2
        val = next(it, None)
        if val is None:
            print(f"{a} requires a value\n{__doc__}", file=sys.stderr)
            return 2
        if a == "--flagship":
            flagship = val
        elif a == "--import":
            imports.append(val)
        elif a == "--hlo":
            hlo_files.append(val)
        elif a == "--baseline":
            baseline_path = val
        elif a == "--write-baseline":
            write_baseline = val
        elif a == "--jsonl":
            jsonl_path = val
        elif a == "--fail-on":
            fail_on = val
        elif a == "--mesh":
            mesh_spec = val
        elif a == "--opt-level":
            opt_level = val
        elif a == "--precision-stats":
            precision_stats = val
    if fail_on not in ("error", "warning", "never"):
        print(f"--fail-on must be error|warning|never, got {fail_on!r}",
              file=sys.stderr)
        return 2
    if opt_level is not None and opt_level not in OPT_LEVELS + ("all",):
        print(f"--opt-level must be O0|O1|O2|O3|all, got {opt_level!r}",
              file=sys.stderr)
        return 2
    if flagship is None and not imports and not hlo_files:
        flagship = "both"

    mesh_model = None
    if mesh_spec is not None:
        from apex_tpu import _compat
        from apex_tpu.lint.mesh_model import parse_mesh_spec
        try:
            try:
                mesh_model = parse_mesh_spec(mesh_spec)
                # CPU runs need the virtual devices BEFORE the backend
                # initializes (a no-op on real accelerators)
                _compat.request_cpu_devices(mesh_model.n_devices)
            except ValueError:
                # specs that infer their local size (Nslice) need a
                # device count — ask for the 8-device CPU audit mesh up
                # front so the count exists before the backend pins it
                # (real accelerators report their own count regardless)
                import jax
                _compat.request_cpu_devices(8)
                mesh_model = parse_mesh_spec(
                    mesh_spec, n_devices=len(jax.devices()))
        except (ValueError, OSError) as e:
            print(f"--mesh: {e}", file=sys.stderr)
            return 2

    targets = []
    if flagship:
        names = list(FLAGSHIP_GROUPS.get(flagship, (flagship,)))
        table = FLAGSHIPS
        if mesh_model is not None:
            # only the DDP-capable flagships have mesh variants; the
            # group aliases narrow to them (the guarded/ckpt self-audit
            # targets are single-program by nature)
            table = MESH_FLAGSHIPS
            if flagship in FLAGSHIP_GROUPS:
                names = [n for n in names if n in MESH_FLAGSHIPS]
        for n in names:
            if n not in table:
                extra = (" (no --mesh variant; drop --mesh or use "
                         f"{'|'.join(MESH_FLAGSHIPS)}|both)"
                         if mesh_model is not None and n in FLAGSHIPS
                         else "")
                print(f"unknown flagship {n!r} (choices: "
                      f"{', '.join(table)}, "
                      f"{', '.join(FLAGSHIP_GROUPS)}){extra}",
                      file=sys.stderr)
                return 2
            if (opt_level is not None and mesh_model is None
                    and n in OPT_LEVEL_FLAGSHIPS):
                levels = (OPT_LEVELS if opt_level == "all"
                          else (opt_level,))
                targets += [("flagship", n, lv) for lv in levels]
            else:
                targets.append(("flagship", n, None))
    targets += [("import", s, None) for s in imports]
    targets += [("hlo", p, None) for p in hlo_files]

    from apex_tpu import lint
    baseline = lint.load_baseline(baseline_path) if baseline_path else []

    precision = None
    if precision_stats is not None:
        from apex_tpu.monitor import numerics as nx
        try:
            with open(precision_stats) as f:
                precision = nx.precision_report(
                    nx.stats_from_json(f.read()))
        except (OSError, ValueError, KeyError) as e:
            print(f"--precision-stats: {e}", file=sys.stderr)
            return 2

    logger = None
    if jsonl_path:
        from apex_tpu import monitor
        logger = monitor.MetricsLogger(
            sinks=[], lint_sink=monitor.JSONLSink(jsonl_path))

    reports, raw_findings = [], []
    for kind, what, lv in targets:
        preflight = None
        if kind == "hlo":
            report = lint.lint_hlo_file(what, mesh_model=mesh_model)
        else:
            if kind == "flagship" and mesh_model is not None:
                mesh = _mesh_for_model(mesh_model, flat_sync=flat_sync)
                # --flat-sync: the builder sees no model, so no
                # comm_plan — the flat sync is the lint subject (the
                # model still judges it below)
                fn, args, policy, name = MESH_FLAGSHIPS[what](
                    mesh, None if flat_sync else mesh_model)
            elif kind == "flagship":
                builder = FLAGSHIPS[what]
                fn, args, policy, name = (builder(opt_level=lv)
                                          if lv is not None
                                          else builder())
            else:
                fn, args, policy, name = _import_builder(what)
            if precision is not None:
                # ONE trace + ONE compile shared by every consumer:
                # lint_step's passes, APX306's schedule walk, and the
                # preflight's static verdict
                import jax
                from apex_tpu.prof import hlo as _hlo
                jaxpr = jax.make_jaxpr(fn)(*args)
                hlo_text = _hlo.compiled_hlo(fn, *args)
                report = lint.lint_step(
                    fn, *args, policy=policy, fn_name=name,
                    mesh_model=mesh_model, precision=precision,
                    jaxpr=jaxpr, hlo_text=hlo_text)
                preflight = lint.precision_preflight(
                    jaxpr, report=precision, policy=policy,
                    hlo_text=hlo_text)
            else:
                report = lint.lint_step(fn, *args, policy=policy,
                                        fn_name=name,
                                        mesh_model=mesh_model)
        # the written baseline must cover EVERYTHING that fired —
        # including findings the read baseline suppresses, or a
        # --baseline X --write-baseline X refresh would drop still-live
        # accepted debt and resurface it as new failures
        raw_findings += report.findings
        report = report.apply_baseline(baseline)
        reports.append(report)
        if as_json:
            out = {"fn": report.fn_name}
            out.update(report.summary())
            if preflight is not None:
                out["preflight"] = {
                    "n_rows": len(preflight.rows),
                    "n_candidates": len(preflight.candidates),
                    "blocking": preflight.blocking,
                    "n_static_sites": preflight.n_sites}
            print(json.dumps(out))
        else:
            print(report.table())
            if preflight is not None:
                print(preflight.table())
        if logger is not None:
            logger.attach_lint_report(report)
    if logger is not None:
        logger.close()

    if write_baseline:
        n = lint.save_baseline(write_baseline, lint.Report(raw_findings))
        print(f"wrote {write_baseline} ({n} suppressions)")

    # severity rank comes from the one canonical ordering (index =
    # sort key) in apex_tpu.lint.SEVERITIES
    sev_rank = {s: i for i, s in enumerate(lint.SEVERITIES)}
    worst = min((sev_rank[r.max_severity()] for r in reports
                 if r.max_severity()), default=99)
    if fail_on != "never" and worst <= sev_rank[fail_on]:
        print(f"apexlint: failing (findings at or above "
              f"--fail-on {fail_on})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
