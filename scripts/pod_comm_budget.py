"""Pod-scale comm evidence: AOT-compile the flagship O2+DDP step
against a v5e-64 topology and audit its collective structure.

No pod hardware is needed: `jax.experimental.topologies` gives 64
abstract v5e devices and the TPU AOT compiler produces the real
optimized HLO for that topology (VERDICT r4 item 5 — the analogue of
the hierarchy the reference hand-builds,
`apex/contrib/optimizers/distributed_fused_adam.py:250-290`,
`apex/parallel/distributed.py:604-624`).

Prints, per DDP mode:
- every collective in the optimized module (op, dtype, bytes,
  replica-group shape),
- the bytes-on-ICI budget: a bidirectional-ring all-reduce moves
  2*(N-1)/N * buffer bytes per chip,
- the weak-scaling prediction against the measured single-chip step.

Usage: python scripts/pod_comm_budget.py [--topology v5e:8x8]
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# measured round-4/5 single-chip numbers (BENCH_TABLE.md)
RESNET_STEP_MS = 97.9       # b=256 device-time isolated step
ICI_BYTES_PER_S = 4.5e11    # v5e per-chip ICI bandwidth class (~450GB/s)

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "pred": 1, "f64": 8, "s64": 8, "u64": 8, "s16": 2,
                "u16": 2, "s8": 1, "u8": 1, "f8e4m3fn": 1,
                "f8e5m2": 1, "f8e4m3fnuz": 1, "f8e5m2fnuz": 1}

_COLL_RE = re.compile(
    r"(all-reduce|reduce-scatter|all-gather|all-to-all|"
    r"collective-permute)(?:-start)?\(")
# fp8 dtypes print as f8e4m3fn[...] — match the full name, not just
# the leading letter+digits
_SHAPE_RE = re.compile(
    r"((?:pred|bf16|f8e[0-9]m[0-9](?:fn|fnuz)?|f16|f32|f64|"
    r"[su](?:8|16|32|64)))\[([0-9,]*)\]")


def collectives(hlo: str):
    """(op, dtype, n_operands, bytes) per collective instruction. A
    combined (variadic) collective has a tuple result shape — every
    element is summed, so a 161-operand fused all-reduce reports its
    full byte count, not its first operand's."""
    out = []
    for line in hlo.splitlines():
        m = _COLL_RE.search(line)
        if not m or "=" not in line:
            continue
        op = m.group(1)
        # result shape(s): everything between '=' and the opcode
        head = line.split(f" {m.group(0)}")[0]
        head = head.split("=", 1)[1] if "=" in head else head
        nbytes, n_ops, dts = 0, 0, set()
        for sm in _SHAPE_RE.finditer(head):
            dt = sm.group(1)
            dims = [int(x) for x in sm.group(2).split(",") if x] or [1]
            nbytes += int(np.prod(dims)) * _DTYPE_BYTES.get(dt, 4)
            n_ops += 1
            dts.add(dt)
        if not n_ops:
            continue
        out.append((op, "+".join(sorted(dts)), n_ops, nbytes))
    return out


def build_step(mesh, delay_allreduce, model=None):
    """The flagship O2+DDP step — ONE definition shared by this
    script's v5e-64 audit and tests/test_pod_hlo.py's CI assertions,
    so what CI pins is exactly what the pod evidence compiled."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp, models, ops, parallel
    from apex_tpu.optim import FusedSGD

    ddp = parallel.DistributedDataParallel(
        mesh, delay_allreduce=delay_allreduce)
    if model is None:
        model = models.ResNet(stage_sizes=[3, 4, 6, 3],
                              num_classes=1000, dtype=jnp.bfloat16)
    amp_opt = amp.Amp(amp.Policy.from_opt_level("O2"),
                      FusedSGD(lr=0.1, momentum=0.9))

    def step(state, batch_stats, xb, yb):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, xb,
                train=True, mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
            return jax.lax.pmean(loss, parallel.DATA_AXIS), \
                mut["batch_stats"]

        (loss, new_bs), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        grads = ddp.sync(grads)
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, new_bs, loss

    return step, model, amp_opt


def lower_flagship(mesh, n, *, delay_allreduce, per_chip_batch=256,
                   model=None, image_size=224):
    """Lower the full ResNet-50 O2+DDP step over ``mesh`` using only
    avals (no real arrays — works on abstract topology devices)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu import parallel

    step, model, amp_opt = build_step(mesh, delay_allreduce,
                                      model=model)

    # shape-only init on the default backend (tiny arrays, real mesh
    # not needed): we just need the state/batch_stats avals
    x1 = jnp.ones((2, image_size, image_size, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x1, train=True))
    params_s, bs_s = variables["params"], variables["batch_stats"]
    state_s = jax.eval_shape(
        lambda: amp_opt.init(jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, a.dtype), params_s)))

    batch = per_chip_batch * n
    x_s = jax.ShapeDtypeStruct((batch, image_size, image_size, 3),
                               jnp.float32)
    y_s = jax.ShapeDtypeStruct((batch,), jnp.int32)

    stepped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(parallel.DATA_AXIS),
                  P(parallel.DATA_AXIS)),
        out_specs=(P(), P(), P()),
        check_vma=False))
    return stepped.lower(state_s, bs_s, x_s, y_s), params_s


def report(hlo, params_s, n):
    colls = collectives(hlo)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params_s))
    grad_bytes = n_params * 4               # fp32 master grads under O2
    print(f"  collectives in optimized HLO ({len(colls)}):")
    total_red = 0
    for op, dt, n_ops, nbytes in colls:
        print(f"    {op:20s} {dt:5s} {n_ops:4d} operands "
              f"{nbytes / 2 ** 20:8.2f} MiB")
        if op in ("all-reduce", "reduce-scatter"):
            total_red += nbytes
    # per-op ring factors: all-reduce moves 2(N-1)/N of the buffer,
    # reduce-scatter and all-gather (N-1)/N each
    ici = 0.0
    for op, dt, n_ops, nbytes in colls:
        if op == "all-reduce":
            ici += 2 * (n - 1) / n * nbytes
        elif op in ("reduce-scatter", "all-gather"):
            ici += (n - 1) / n * nbytes
    t_ms = ici / ICI_BYTES_PER_S * 1e3
    eff = RESNET_STEP_MS / (RESNET_STEP_MS + t_ms)
    print(f"  param bytes (fp32 grads): {grad_bytes / 2 ** 20:.1f} MiB; "
          f"reduced bytes: {total_red / 2 ** 20:.1f} MiB")
    print(f"  ring ICI traffic/chip/step: {ici / 2 ** 20:.1f} MiB "
          f"-> {t_ms:.2f} ms at {ICI_BYTES_PER_S / 1e9:.0f} GB/s")
    print(f"  unoverlapped weak-scaling efficiency vs "
          f"{RESNET_STEP_MS} ms step: {eff * 100:.1f}%")


def main():
    topology = "v5e:8x8"
    if "--topology" in sys.argv:
        topology = sys.argv[sys.argv.index("--topology") + 1]
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from apex_tpu import parallel

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topology)
    n = len(topo.devices)
    mesh = Mesh(np.array(topo.devices), (parallel.DATA_AXIS,))
    print(f"AOT target: {topology} ({n} chips)")

    for delay in (True, False):
        print(f"\nDDP delay_allreduce={delay} "
              f"({'one flat fused reduce per dtype' if delay else 'per-tensor psum + XLA combiner'}):")
        lowered, params_s = lower_flagship(mesh, n,
                                           delay_allreduce=delay)
        hlo = lowered.compile().as_text()
        report(hlo, params_s, n)


if __name__ == "__main__":
    main()
