"""ASP 2:4 structured sparsity — masks and optimizer integration.

Mirrors `apex/contrib/sparsity/test/*` (mask structure, prune-after-step
invariant, checkpoint round-trip) plus direct oracle checks of the 2d
block algorithms against the reference semantics
(`sparse_masklib.py:69-97,123-139`).
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import sparsity
from apex_tpu.sparsity import masklib


def _greedy_oracle_block(block4x4):
    """The reference mn_2d_greedy inner loop (`sparse_masklib.py:78-97`)
    on one 4x4 block, in plain numpy."""
    mat = np.abs(block4x4).reshape(-1)
    mask = np.zeros(16)
    rowc = collections.Counter()
    colc = collections.Counter()
    for idx in np.argsort(mat)[::-1]:
        r, c = int(idx) // 4, int(idx) % 4
        if rowc[r] == 2 or colc[c] == 2:
            continue
        mask[idx] = 1
        rowc[r] += 1
        colc[c] += 1
    return mask.reshape(4, 4).astype(bool)


class TestMasks1d:
    def test_two_of_four_kept(self):
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(8, 16).astype(np.float32))
        m = masklib.m4n2_1d(w)
        groups = np.asarray(m).reshape(8, 4, 4)
        np.testing.assert_array_equal(groups.sum(-1), 2)

    def test_keeps_largest_magnitudes(self):
        w = jnp.asarray([[0.1, -5.0, 3.0, 0.2]])
        m = np.asarray(masklib.m4n2_1d(w))
        np.testing.assert_array_equal(m, [[False, True, True, False]])

    def test_tail_kept_dense(self):
        w = jnp.ones((2, 7))
        m = np.asarray(masklib.m4n2_1d(w))
        assert m[:, 4:].all()


class TestMasks2d:
    def test_greedy_matches_reference_oracle(self):
        """Vectorized greedy == the reference's per-block loop."""
        rng = np.random.RandomState(1)
        w = rng.randn(12, 16).astype(np.float32)
        got = np.asarray(masklib.m4n2_2d_greedy(jnp.asarray(w)))
        for br in range(3):
            for bc in range(4):
                blk = w[br * 4:br * 4 + 4, bc * 4:bc * 4 + 4]
                ref = _greedy_oracle_block(blk)
                np.testing.assert_array_equal(
                    got[br * 4:br * 4 + 4, bc * 4:bc * 4 + 4], ref,
                    err_msg=f"block ({br},{bc})")

    @pytest.mark.parametrize("fn,exact", [(masklib.m4n2_2d_greedy, False),
                                          (masklib.m4n2_2d_best, True)])
    def test_doubly_structured(self, fn, exact):
        """Every 4x4 block is 2:4 along rows AND columns — the property
        that makes the transposed (dgrad) weight sparse too. The greedy
        fill can strand a row/column at 1 kept entry (the reference loop
        has the identical skip, `sparse_masklib.py:90-92`), so it only
        guarantees AT MOST 2 — still a valid 2:4 hardware pattern; the
        exhaustive search always keeps exactly 2."""
        rng = np.random.RandomState(2)
        w = jnp.asarray(rng.randn(16, 24).astype(np.float32))
        m = np.asarray(fn(w)).astype(int)
        blocks = m.reshape(4, 4, 6, 4).transpose(0, 2, 1, 3)
        if exact:
            np.testing.assert_array_equal(blocks.sum(-1), 2)   # rows
            np.testing.assert_array_equal(blocks.sum(-2), 2)   # columns
        else:
            assert (blocks.sum(-1) <= 2).all()
            assert (blocks.sum(-2) <= 2).all()
            assert blocks.sum() >= 0.9 * 2 * 4 * blocks.shape[0] \
                * blocks.shape[1]

    def test_best_at_least_as_good_as_greedy(self):
        """Exhaustive search preserves >= magnitude vs greedy on every
        block (the reason mn_2d_best exists)."""
        rng = np.random.RandomState(3)
        w = jnp.asarray(rng.randn(32, 32).astype(np.float32))
        a = np.abs(np.asarray(w))
        kept_best = (a * np.asarray(masklib.m4n2_2d_best(w))).sum()
        kept_greedy = (a * np.asarray(masklib.m4n2_2d_greedy(w))).sum()
        assert kept_best >= kept_greedy - 1e-5

    def test_tail_rows_cols_dense(self):
        w = jnp.ones((6, 9))
        m = np.asarray(masklib.m4n2_2d_greedy(w))
        assert m[4:, :].all() and m[:, 8:].all()

    def test_batched_leading_dims(self):
        rng = np.random.RandomState(4)
        w = jnp.asarray(rng.randn(3, 8, 8).astype(np.float32))
        m = np.asarray(masklib.m4n2_2d_best(w)).astype(int)
        for i in range(3):
            blocks = m[i].reshape(2, 4, 2, 4).transpose(0, 2, 1, 3)
            np.testing.assert_array_equal(blocks.sum(-1), 2)
            np.testing.assert_array_equal(blocks.sum(-2), 2)

    def test_jittable(self):
        rng = np.random.RandomState(5)
        w = jnp.asarray(rng.randn(8, 8).astype(np.float32))
        m1 = jax.jit(masklib.m4n2_2d_greedy)(w)
        np.testing.assert_array_equal(np.asarray(m1),
                                      np.asarray(masklib.m4n2_2d_greedy(w)))


class TestASP:
    def _params(self):
        rng = np.random.RandomState(6)
        return {
            "dense": {"kernel": jnp.asarray(
                rng.randn(16, 8).astype(np.float32)),
                "bias": jnp.zeros(8)},
            "norm": {"scale": jnp.ones(8)},
        }

    def test_whitelist(self):
        masks = sparsity.compute_sparse_masks(self._params())
        assert masks["dense"]["kernel"] is not None
        assert masks["dense"]["bias"] is None
        assert masks["norm"]["scale"] is None

    def test_params_stay_pruned_after_step(self):
        """The patched-step invariant (`asp.py:127-153`): after every
        update, whitelisted weights still satisfy the mask."""
        from apex_tpu.optim import FusedSGD
        params = self._params()
        asp = sparsity.ASP(FusedSGD(lr=0.5, momentum=0.9))
        state = asp.init(params)
        g = jax.tree_util.tree_map(jnp.ones_like, params)
        p = params
        for _ in range(3):
            p, state = asp.step(g, state, p)
        k = np.asarray(p["dense"]["kernel"])
        m = np.asarray(state.masks["dense"]["kernel"])
        assert (k[~m] == 0).all()
        assert (k[m] != 0).any()
        groups = m.reshape(16, 2, 4)
        np.testing.assert_array_equal(groups.sum(-1), 2)

    def test_checkpoint_roundtrip(self):
        """ASPState is a pytree: save/restore continues training bitwise
        (`sparsity/test/checkpointing_*` capability)."""
        from apex_tpu.optim import FusedSGD
        params = self._params()
        asp = sparsity.ASP(FusedSGD(lr=0.1, momentum=0.9),
                           pattern="m4n2_2d_best")
        state = asp.init(params)
        g = jax.tree_util.tree_map(jnp.ones_like, params)
        p1, s1 = asp.step(g, state, params)

        # round-trip through host numpy (what any checkpointer does)
        restored = jax.tree_util.tree_map(
            lambda x: x if x is None else jnp.asarray(np.asarray(x)), s1,
            is_leaf=lambda x: x is None)
        p2a, _ = asp.step(g, s1, p1)
        p2b, _ = asp.step(g, restored, p1)
        np.testing.assert_array_equal(np.asarray(p2a["dense"]["kernel"]),
                                      np.asarray(p2b["dense"]["kernel"]))
