"""End-to-end Amp bundle tests: init → train steps → overflow → checkpoint.

Functional mirror of `tests/L0/run_amp/test_checkpointing.py` and the
multi-loss DCGAN pattern (`examples/dcgan/main_amp.py:215-253`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp


def _toy_params(key=0):
    k = jax.random.PRNGKey(key)
    return {"dense": {"kernel": jax.random.normal(k, (4, 4)),
                      "bias": jnp.zeros((4,))}}


def _loss_fn(model_params, x):
    y = x @ model_params["dense"]["kernel"] + model_params["dense"]["bias"]
    return jnp.mean(jnp.square(y))


class TestAmpStep:
    @pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
    def test_loss_decreases(self, opt_level):
        amp_opt, state = amp.initialize(
            _toy_params(), optax.sgd(0.1), opt_level)
        x = jnp.ones((8, 4))

        @jax.jit
        def step(state):
            return amp_opt.step(state, _loss_fn, x)

        losses = []
        for _ in range(10):
            state, loss, finite = step(state)
            assert bool(finite)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_o2_masters_stay_fp32(self):
        amp_opt, state = amp.initialize(_toy_params(), optax.sgd(0.1), "O2")
        assert state.params["dense"]["kernel"].dtype == jnp.float32
        model_p = amp_opt.model_params(state)
        assert model_p["dense"]["kernel"].dtype == jnp.bfloat16

    def test_o3_params_half(self):
        amp_opt, state = amp.initialize(_toy_params(), optax.sgd(0.1), "O3")
        assert state.params["dense"]["kernel"].dtype == jnp.bfloat16

    def test_fp16_overflow_skips_step(self):
        """Poisoned grads: step must not move params, scale must halve
        (`test_fused_sgd.py` overflow-injection pattern)."""
        amp_opt, state = amp.initialize(
            _toy_params(), optax.sgd(0.1), "O2", half_dtype=jnp.float16)

        def bad_loss(model_params, x):
            return jnp.sum(model_params["dense"]["kernel"]) * jnp.inf

        before = np.asarray(state.params["dense"]["kernel"])
        scale_before = float(state.scalers[0].loss_scale)
        state, _, finite = jax.jit(
            lambda s: amp_opt.step(s, bad_loss, jnp.ones((2, 4))))(state)
        assert not bool(finite)
        np.testing.assert_array_equal(
            np.asarray(state.params["dense"]["kernel"]), before)
        assert float(state.scalers[0].loss_scale) == scale_before / 2
        assert int(state.step) == 0  # skipped steps don't count

    def test_multi_loss_independent_scalers(self):
        amp_opt, state = amp.initialize(
            _toy_params(), optax.sgd(0.1), "O2", half_dtype=jnp.float16,
            num_losses=2)

        def bad_loss(mp, x):
            return jnp.sum(mp["dense"]["kernel"]) * jnp.inf

        _, _, state, finite = amp_opt.backward(
            state, bad_loss, jnp.ones((2, 4)), loss_id=1)
        assert not bool(finite)
        # scaler 1 backed off; scaler 0 untouched
        assert float(state.scalers[1].loss_scale) == 2.0 ** 15
        assert float(state.scalers[0].loss_scale) == 2.0 ** 16

    def test_state_dict_roundtrip(self):
        amp_opt, state = amp.initialize(
            _toy_params(), optax.sgd(0.1), "O2", half_dtype=jnp.float16)
        # advance the scaler, then round-trip through state_dict
        _, _, state, _ = amp_opt.backward(
            state, _loss_fn, jnp.ones((2, 4)))
        sd = amp_opt.state_dict(state)
        fresh = amp_opt.init(_toy_params())
        restored = amp_opt.load_state_dict(fresh, sd)
        assert (float(restored.scalers[0].loss_scale)
                == float(state.scalers[0].loss_scale))
        assert (int(restored.scalers[0].growth_tracker)
                == int(state.scalers[0].growth_tracker))

    def test_checkpoint_resume_continues_identically(self):
        """Train 3 steps, checkpoint (pytree), restore, continue — identical
        to an uninterrupted run (`test_checkpointing.py:1-267` semantics)."""
        tx = optax.adam(1e-2)
        amp_opt, state = amp.initialize(_toy_params(), tx, "O2")
        x = jnp.ones((8, 4))
        step = jax.jit(lambda s: amp_opt.step(s, _loss_fn, x))

        for _ in range(3):
            state, _, _ = step(state)
        # "checkpoint": the whole AmpState is a pytree; serialize via numpy
        ckpt = jax.tree_util.tree_map(np.asarray, state)
        restored = jax.tree_util.tree_map(jnp.asarray, ckpt)

        out_a, out_b = state, restored
        for _ in range(3):
            out_a, la, _ = step(out_a)
            out_b, lb, _ = step(out_b)
            assert float(la) == float(lb)
        np.testing.assert_array_equal(
            np.asarray(out_a.params["dense"]["kernel"]),
            np.asarray(out_b.params["dense"]["kernel"]))


class TestFlaxAutoCast:
    """O1 ergonomics on an unmodified flax model."""

    def _model(self):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.Dense(8)(x)
                x = nn.LayerNorm()(x)
                x = nn.Dense(4)(x)
                return x
        return Net()

    def test_auto_cast_runs_dense_in_half(self):
        import flax.linen as nn
        model = self._model()
        x = jnp.ones((2, 8))
        variables = model.init(jax.random.PRNGKey(0), x)
        policy = amp.Policy.from_opt_level("O1")

        seen = {}
        half_mods, float_mods = (nn.Dense,), (nn.LayerNorm,)

        def spy(next_fun, args, kwargs, context):
            if isinstance(context.module, half_mods + float_mods) \
                    and context.method_name == "__call__":
                seen.setdefault(type(context.module).__name__,
                                jnp.asarray(args[0]).dtype)
            return next_fun(*args, **kwargs)

        with amp.auto_cast(policy):
            with nn.intercept_methods(spy):
                out = model.apply(variables, x)
        assert seen["Dense"] == jnp.bfloat16      # whitelist cast
        assert seen["LayerNorm"] == jnp.float32   # blacklist cast
        # params stayed fp32 (O1 keeps fp32 weights)
        assert variables["params"]["Dense_0"]["kernel"].dtype == jnp.float32

    def test_auto_cast_grads_flow(self):
        model = self._model()
        x = jnp.ones((2, 8))
        variables = model.init(jax.random.PRNGKey(0), x)
        policy = amp.Policy.from_opt_level("O1")

        def loss(params):
            with amp.auto_cast(policy):
                return jnp.mean(model.apply({"params": params}, x) ** 2)

        grads = jax.grad(loss)(variables["params"])
        # grads are w.r.t. fp32 params
        assert grads["Dense_0"]["kernel"].dtype == jnp.float32
        assert float(jnp.abs(grads["Dense_0"]["kernel"]).sum()) > 0


class TestDecorators:
    def test_half_float_promote(self):
        policy = amp.Policy.from_opt_level("O1")

        @amp.half_function
        def h(x):
            return x.dtype

        @amp.float_function
        def f(x):
            return x.dtype

        @amp.promote_function
        def p(x, y):
            return x.dtype, y.dtype

        x32 = jnp.ones((2,), jnp.float32)
        x16 = jnp.ones((2,), jnp.bfloat16)
        with amp.policy_scope(policy):
            assert h(x32) == jnp.bfloat16
            assert f(x16) == jnp.float32
            assert p(x16, x32) == (jnp.float32, jnp.float32)
        # outside the scope: no casting
        assert h(x32) == jnp.float32


class TestInterceptorCoverage:
    def test_dtype_restored_after_auto_cast(self):
        """A module instance reused outside auto_cast must be unaffected —
        the dtype retarget is scoped to the intercepted call (the reference
        restores patched functions on handle exit, `handle.py:170-252`)."""
        import flax.linen as nn
        model = nn.Dense(4)
        x = jnp.ones((2, 8))
        variables = model.init(jax.random.PRNGKey(0), x)
        policy = amp.Policy.from_opt_level("O1")

        with amp.auto_cast(policy):
            y_in = model.apply(variables, x)
        assert model.dtype is None, "dtype retarget leaked out of the call"
        y_out = model.apply(variables, x)
        assert y_in.dtype == jnp.bfloat16
        assert y_out.dtype == jnp.float32, \
            "module reused outside auto_cast must compute fp32"

    def test_embed_covered(self):
        """nn.Embed is whitelisted: the lookup result comes out half so the
        downstream matmuls run on the MXU."""
        import flax.linen as nn
        emb = nn.Embed(16, 8)
        ids = jnp.arange(4)
        variables = emb.init(jax.random.PRNGKey(0), ids)
        policy = amp.Policy.from_opt_level("O1")
        with amp.auto_cast(policy):
            out = emb.apply(variables, ids)
        assert out.dtype == jnp.bfloat16
        assert variables["params"]["embedding"].dtype == jnp.float32

    def test_user_registration_precedence(self):
        """A user-registered module class out-prioritises the built-in
        tables (`apex/amp/amp.py:94-114` semantics) — here a LayerNorm
        subclass forced to half."""
        import flax.linen as nn

        class HalfNorm(nn.LayerNorm):
            pass

        amp.register_half_module(HalfNorm)
        try:
            m = HalfNorm()
            x = jnp.ones((2, 8))
            variables = m.init(jax.random.PRNGKey(0), x)
            policy = amp.Policy.from_opt_level("O1")
            with amp.auto_cast(policy):
                y = m.apply(variables, x)
            assert y.dtype == jnp.bfloat16, \
                "user half registration must beat the builtin blacklist"
        finally:
            from apex_tpu.amp import lists
            lists._EXTRA_HALF_MODULES.remove(HalfNorm)

    def test_explicit_user_dtype_wins(self):
        """A module constructed with an explicit dtype is never retargeted."""
        import flax.linen as nn
        model = nn.Dense(4, dtype=jnp.float32)
        x = jnp.ones((2, 8))
        variables = model.init(jax.random.PRNGKey(0), x)
        policy = amp.Policy.from_opt_level("O1")
        with amp.auto_cast(policy):
            y = model.apply(variables, x)
        assert y.dtype == jnp.float32


class TestGradAccumulation:
    """Microbatch accumulation across backwards — `apex/amp/scaler.py:152-190`
    (unscale_with_stashed) + `_process_optimizer.py:142-158` semantics."""

    def _setup(self, opt_level="O2", **overrides):
        from apex_tpu.optim import FusedSGD
        policy = amp.Policy.from_opt_level(opt_level, **overrides)
        amp_opt = amp.Amp(policy, FusedSGD(lr=0.1))
        params = {"w": jnp.arange(8.0) / 8.0}
        return amp_opt, amp_opt.init(params)

    @staticmethod
    def _loss(mp, xb):
        return jnp.sum(jnp.square(xb * mp["w"].astype(jnp.float32)))

    @pytest.mark.parametrize("opt_level,rtol", [("O0", 1e-6), ("O2", 1e-2)])
    def test_accumulated_equals_full_batch(self, opt_level, rtol):
        """O0 is exact; O2 matches to bf16 grad precision (each microbatch
        grad rounds through the model-dtype cast, like the reference's
        fp16 model grads)."""
        amp_opt, state = self._setup(opt_level)
        x = jnp.arange(32.0).reshape(4, 8) / 32.0

        # 4 microbatches accumulated
        acc, fin, st = None, True, state
        for i in range(4):
            _, acc, st, fin = amp_opt.backward_accumulate(
                st, self._loss, x[i], stashed=acc, finite=fin)
        st_acc = amp_opt.apply_gradients(st, acc, fin)

        # one backward of the summed loss
        def full(mp):
            return sum(self._loss(mp, x[i]) for i in range(4))
        _, g, st2, f2 = amp_opt.backward(state, full)
        st_full = amp_opt.apply_gradients(st2, g, f2)

        np.testing.assert_allclose(np.asarray(st_acc.params["w"]),
                                   np.asarray(st_full.params["w"]),
                                   rtol=rtol, atol=rtol)

    def test_overflow_in_one_microbatch_skips_step(self):
        # fp16 half dtype => dynamic scaler => overflow machinery active
        amp_opt, state = self._setup("O2", half_dtype=jnp.float16)
        x = jnp.ones((2, 8))
        bad = jnp.full((8,), jnp.inf)

        acc, fin, st = None, True, state
        _, acc, st, fin = amp_opt.backward_accumulate(
            st, self._loss, x[0], stashed=acc, finite=fin)
        _, acc, st, fin = amp_opt.backward_accumulate(
            st, self._loss, bad, stashed=acc, finite=fin)
        assert not bool(fin)
        stepped = amp_opt.apply_gradients(st, acc, fin)
        np.testing.assert_array_equal(np.asarray(stepped.params["w"]),
                                      np.asarray(state.params["w"]))
        assert int(stepped.step) == 0

    def test_scale_advances_between_microbatches(self):
        """The dynamic-scale schedule ticks per backward (the reference
        updates per unscale); accumulation must stay correct across the
        scale change."""
        amp_opt, state = self._setup("O2", half_dtype=jnp.float16)
        assert amp_opt.scale_cfg is not None
        # force growth every backward so microbatches see different scales
        from apex_tpu.amp.scaler import LossScaleConfig
        amp_opt.scale_cfg = LossScaleConfig(
            dynamic=True, init_scale=2.0**4, growth_interval=1)
        state = amp_opt.init({"w": jnp.arange(8.0) / 8.0})
        x = jnp.arange(16.0).reshape(2, 8) / 16.0

        acc, fin, st = None, True, state
        for i in range(2):
            _, acc, st, fin = amp_opt.backward_accumulate(
                st, self._loss, x[i], stashed=acc, finite=fin)
        assert float(st.scalers[0].loss_scale) > 2.0**4  # scale moved
        ref = jax.grad(lambda mp: self._loss(mp, x[0])
                       + self._loss(mp, x[1]))({"w": jnp.arange(8.0) / 8.0})
        np.testing.assert_allclose(np.asarray(acc["w"]),
                                   np.asarray(ref["w"]), rtol=1e-5)

    def test_under_scan(self):
        """The accumulation loop must be lax.scan-able (static structure)."""
        amp_opt, state = self._setup()
        x = jnp.arange(32.0).reshape(4, 8) / 32.0
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), state.params)

        def body(carry, xb):
            st, acc, fin = carry
            _, acc, st, fin = amp_opt.backward_accumulate(
                st, self._loss, xb, stashed=acc, finite=fin)
            return (st, acc, fin), ()

        (st, acc, fin), _ = jax.lax.scan(
            body, (state, zeros, jnp.bool_(True)), x)
        stepped = amp_opt.apply_gradients(st, acc, fin)
        assert int(stepped.step) == 1


class TestLegacySurfaces:
    """Deprecated-API shims: amp.opt.OptimWrapper (`apex/amp/opt.py:9-103`)
    and the contrib externally-scaled-grads optimizers
    (`apex/contrib/optimizers/fused_adam.py:64-206`)."""

    def test_optim_wrapper_two_losses(self):
        from apex_tpu.optim import FusedSGD
        w = amp.OptimWrapper(FusedSGD(lr=0.1), num_loss=2)
        params = {"w": jnp.arange(8.0) / 8.0}
        ws = w.init(params)
        x = jnp.arange(8.0)

        def l0(p):
            return jnp.sum(jnp.square(p["w"] * x))

        def l1(p):
            return jnp.sum(jnp.abs(p["w"]))

        out0, acc, ws = w.backward(ws, params, l0, 0, None)
        out1, acc, ws = w.backward(ws, params, l1, 1, acc)
        new_p, ws = w.step(ws, acc, params)

        ref = jax.grad(lambda p: l0(p) + l1(p))(params)
        np.testing.assert_allclose(
            np.asarray(new_p["w"]),
            np.asarray(params["w"] - 0.1 * ref["w"]), rtol=1e-5,
            atol=1e-7)
        assert len(w.loss_scale(ws)) == 2

    def test_optim_wrapper_overflow_skips(self):
        from apex_tpu.optim import FusedSGD
        w = amp.OptimWrapper(FusedSGD(lr=0.1), num_loss=2)
        params = {"w": jnp.ones(4)}
        ws = w.init(params)

        def good(p):
            return jnp.sum(p["w"])

        def bad(p):
            return jnp.sum(p["w"]) * jnp.float32(jnp.inf)

        _, acc, ws = w.backward(ws, params, good, 0, None)
        s1_before = float(ws["scalers"][1].loss_scale)
        _, acc, ws = w.backward(ws, params, bad, 1, acc)
        new_p, ws = w.step(ws, acc, params)
        np.testing.assert_array_equal(np.asarray(new_p["w"]),
                                      np.asarray(params["w"]))
        # only loss 1's scaler backed off; flag reset after step
        assert float(ws["scalers"][1].loss_scale) == s1_before / 2
        assert bool(ws["finite"])

    def test_legacy_fused_adam_scale_and_copy(self):
        """step(grads, scale=..., output_dtype=...) unscales in-kernel and
        emits the reduced-precision copy in the same pass."""
        from apex_tpu.optim import legacy, FusedAdam

        params = {"w": jnp.arange(16.0) / 16.0}
        g = {"w": jnp.ones(16) * 128.0}          # scaled by 128
        lo = legacy.FusedAdam(lr=1e-2)
        ls = lo.init(params)
        p1, ls, copy = lo.step(g, ls, params, scale=128.0,
                               output_dtype=jnp.bfloat16)
        assert copy["w"].dtype == jnp.bfloat16

        modern = FusedAdam(lr=1e-2)
        ms = modern.init(params)
        p2, _ = modern.step({"w": jnp.ones(16)}, ms, params)
        np.testing.assert_allclose(np.asarray(p1["w"]),
                                   np.asarray(p2["w"]), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(copy["w"], np.float32),
                                   np.asarray(p1["w"]), atol=4e-3)

    def test_legacy_fused_sgd_scale(self):
        from apex_tpu.optim import legacy, FusedSGD
        params = {"w": jnp.arange(8.0)}
        g = {"w": jnp.full(8, 64.0)}
        lo = legacy.FusedSGD(lr=0.5, momentum=0.9)
        ls = lo.init(params)
        p1, ls = lo.step(g, ls, params, scale=64.0)
        modern = FusedSGD(lr=0.5, momentum=0.9)
        p2, _ = modern.step({"w": jnp.ones(8)}, modern.init(params), params)
        np.testing.assert_allclose(np.asarray(p1["w"]),
                                   np.asarray(p2["w"]), rtol=1e-6)

    def test_legacy_fused_lamb_parity_and_scale(self):
        """legacy.FusedLAMB at scale=1 matches optim.FusedLAMB (arena
        strategy) bit-for-bit in math; scaled grads land identically
        (`contrib/optimizers/fused_lamb.py` capability)."""
        from apex_tpu.optim import legacy, FusedLAMB

        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(32, 8), jnp.float32),
                  "b": jnp.asarray(rng.randn(8), jnp.float32)}
        grads = {"w": jnp.asarray(rng.randn(32, 8), jnp.float32),
                 "b": jnp.asarray(rng.randn(8), jnp.float32)}

        lo = legacy.FusedLAMB(lr=1e-2, weight_decay=0.01)
        ls = lo.init(params)
        p1, ls = lo.step(grads, ls, params, scale=1.0)

        modern = FusedLAMB(lr=1e-2, weight_decay=0.01, strategy="arena")
        ms = modern.init(params)
        p2, _ = modern.step(grads, ms, params)
        for k in params:
            np.testing.assert_allclose(np.asarray(p1[k]),
                                       np.asarray(p2[k]), rtol=1e-6)

        # scaled grads + copy-out: same result as unscaled, bf16 copy
        lo2 = legacy.FusedLAMB(lr=1e-2, weight_decay=0.01)
        sg = jax.tree_util.tree_map(lambda g: g * 256.0, grads)
        p3, _, copy = lo2.step(sg, lo2.init(params), params, scale=256.0,
                               output_dtype=jnp.bfloat16)
        for k in params:
            np.testing.assert_allclose(np.asarray(p3[k]),
                                       np.asarray(p1[k]), rtol=1e-5)
            assert copy[k].dtype == jnp.bfloat16

    def test_legacy_fused_lamb_clip_and_nvlamb_paths(self):
        """max_grad_norm=0 disables the clip (pure 1/scale); use_nvlamb
        applies trust ratios even at wd=0 — both mirror the modern
        surface at scale=1."""
        from apex_tpu.optim import legacy, FusedLAMB

        rng = np.random.RandomState(1)
        params = {"w": jnp.asarray(rng.randn(16, 8), jnp.float32)}
        grads = {"w": jnp.asarray(rng.randn(16, 8) * 3.0, jnp.float32)}

        for kw in ({"max_grad_norm": 0.0, "weight_decay": 0.0},
                   {"use_nvlamb": True, "weight_decay": 0.0},
                   {"max_grad_norm": 0.5}):
            lo = legacy.FusedLAMB(lr=1e-2, **kw)
            p1, _ = lo.step(grads, lo.init(params), params, scale=1.0)
            modern = FusedLAMB(lr=1e-2, strategy="arena", **kw)
            p2, _ = modern.step(grads, modern.init(params), params)
            np.testing.assert_allclose(np.asarray(p1["w"]),
                                       np.asarray(p2["w"]), rtol=1e-6,
                                       err_msg=str(kw))


class TestFunctionalPatch:
    """O1 raw-op coverage: jnp/lax entry points under auto_cast
    (`apex/amp/amp.py:68-177` analogue, VERDICT round-2 item 6)."""

    def test_raw_einsum_runs_half_under_o1(self):
        policy = amp.Policy.from_opt_level("O1")
        a = jnp.ones((8, 16), jnp.float32)
        b = jnp.ones((16, 4), jnp.float32)
        with amp.auto_cast(policy):
            out_e = jnp.einsum("ij,jk->ik", a, b)
            out_m = jnp.matmul(a, b)
            out_c = jax.lax.conv_general_dilated(
                jnp.ones((1, 8, 8, 3), jnp.float32),
                jnp.ones((3, 3, 3, 4), jnp.float32),
                window_strides=(1, 1), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        assert out_e.dtype == jnp.bfloat16
        assert out_m.dtype == jnp.bfloat16
        assert out_c.dtype == jnp.bfloat16
        # blacklist entry points go fp32 even on half inputs
        with amp.auto_cast(policy):
            s = jax.nn.softmax(jnp.ones((4, 4), jnp.bfloat16))
        assert s.dtype == jnp.float32

    def test_raw_op_user_registry(self):
        """User-owned (module, attr) targets get the functional-patch
        treatment via register_half_op/register_float_op — the
        reference's arbitrary-function O1 registration
        (`apex/amp/amp.py:30-64`)."""
        import types
        from apex_tpu.amp import functional_patch as fp

        ns = types.SimpleNamespace(
            mm=lambda a, b: jnp.matmul(a, b),
            sm=lambda a: jax.nn.softmax(a))
        policy = amp.Policy.from_opt_level("O1")
        a = jnp.ones((4, 4), jnp.float32)
        orig_mm, orig_sm = ns.mm, ns.sm
        try:
            amp.register_half_op((ns, "mm"))
            amp.register_float_op((ns, "sm"))
            with amp.auto_cast(policy):
                assert ns.mm is not orig_mm
                assert ns.mm(a, a).dtype == jnp.bfloat16
                assert ns.sm(a.astype(jnp.bfloat16)).dtype == jnp.float32
            # originals restored on exit
            assert ns.mm is orig_mm and ns.sm is orig_sm
            # outside any scope: passthrough
            assert ns.mm(a, a).dtype == jnp.float32

            # registering INSIDE a live scope takes effect immediately,
            # and re-registering with the other kind moves the target.
            # The body is a neutral op: a body calling a *half-listed*
            # entry point would legitimately re-cast inside (innermost
            # policy wins, as with nested auto_cast).
            ns.late = lambda a, b: a + b
            orig_late = ns.late
            with amp.auto_cast(policy):
                amp.register_half_op((ns, "late"))
                assert ns.late(a, a).dtype == jnp.bfloat16
                amp.register_float_op((ns, "late"))
                assert ns.late(a.astype(jnp.bfloat16),
                               a.astype(jnp.bfloat16)).dtype \
                    == jnp.float32
            assert ns.late is orig_late
            # nesting still composes and restores with user targets in
            with amp.auto_cast(policy):
                with amp.auto_cast(policy):
                    assert getattr(ns.mm, "__wrapped_by_apex_tpu__",
                                   False)
                assert ns.mm is not orig_mm
            assert ns.mm is orig_mm
        finally:
            amp.unregister_op((ns, "mm"))
            amp.unregister_op((ns, "sm"))
            amp.unregister_op((ns, "late"))
        assert not any(t[0] is ns for t in fp._USER_HALF_TARGETS)
        assert not any(t[0] is ns for t in fp._USER_FLOAT_TARGETS)

    def test_raw_op_registry_builtin_overlap(self):
        """Registering a target that overlaps a BUILT-IN patched entry
        must not stack wrappers or leak one past scope exit (the
        first-pushed original is restored on re-registration)."""
        from apex_tpu.amp import functional_patch as fp
        policy = amp.Policy.from_opt_level("O1")
        a = jnp.ones((4, 4), jnp.float32)
        orig_mm = jnp.matmul
        try:
            amp.register_half_op((jnp, "matmul"))   # overlaps built-in
            with amp.auto_cast(policy):
                assert jnp.matmul(a, a).dtype == jnp.bfloat16
                # move it to float inside the live scope
                amp.register_float_op((jnp, "matmul"))
                assert jnp.matmul(
                    a.astype(jnp.bfloat16),
                    a.astype(jnp.bfloat16)).dtype == jnp.float32
            assert jnp.matmul is orig_mm, "stale wrapper leaked"
            # a later scope applies the user's final (float) choice
            with amp.auto_cast(policy):
                assert jnp.matmul(
                    a.astype(jnp.bfloat16),
                    a.astype(jnp.bfloat16)).dtype == jnp.float32
            assert jnp.matmul is orig_mm
        finally:
            amp.unregister_op((jnp, "matmul"))
        # unregister inside a live scope restores immediately
        ns2 = __import__("types").SimpleNamespace(f=lambda a: a + a)
        orig_f = ns2.f
        with amp.auto_cast(policy):
            amp.register_half_op((ns2, "f"))
            assert ns2.f is not orig_f
            amp.unregister_op((ns2, "f"))
            assert ns2.f is orig_f
        assert ns2.f is orig_f

    def test_functional_patch_restores(self):
        policy = amp.Policy.from_opt_level("O1")
        orig_einsum = jnp.einsum
        orig_conv = jax.lax.conv_general_dilated
        with amp.auto_cast(policy):
            assert jnp.einsum is not orig_einsum
            with amp.auto_cast(policy):   # nesting composes
                assert getattr(jnp.einsum,
                               "__wrapped_by_apex_tpu__", False)
            assert jnp.einsum is not orig_einsum
        assert jnp.einsum is orig_einsum
        assert jax.lax.conv_general_dilated is orig_conv
        # restore also on exception
        try:
            with amp.auto_cast(policy):
                raise RuntimeError("boom")
        except RuntimeError:
            pass
        assert jnp.einsum is orig_einsum

    def test_o2_does_not_patch(self):
        policy = amp.Policy.from_opt_level("O2")
        orig = jnp.einsum
        with amp.auto_cast(policy):
            assert jnp.einsum is orig

    def test_nested_policies_innermost_wins(self):
        p_bf16 = amp.Policy.from_opt_level("O1", half_dtype=jnp.bfloat16)
        p_fp16 = amp.Policy.from_opt_level("O1", half_dtype=jnp.float16)
        a = jnp.ones((4, 4), jnp.float32)
        with amp.auto_cast(p_bf16):
            assert jnp.matmul(a, a).dtype == jnp.bfloat16
            with amp.auto_cast(p_fp16):
                assert jnp.matmul(a, a).dtype == jnp.float16
            assert jnp.matmul(a, a).dtype == jnp.bfloat16

    def test_explicit_module_dtype_not_overridden_by_patch(self):
        """A flax module with explicit dtype=float32 keeps fp32 compute
        under O1 even though its body calls the patched lax.conv entry
        point (interceptor suspends the raw-op patch inside)."""
        import flax.linen as nn

        policy = amp.Policy.from_opt_level("O1")
        conv = nn.Conv(4, (3, 3), dtype=jnp.float32)
        x = jnp.ones((1, 8, 8, 3), jnp.float32)
        variables = conv.init(jax.random.PRNGKey(0), x)
        with amp.auto_cast(policy):
            out = conv.apply(variables, x)
        assert out.dtype == jnp.float32

    def test_fp32_oracle_unaffected_by_patch(self):
        from apex_tpu import ops

        policy = amp.Policy.from_opt_level("O1")
        q = jnp.ones((1, 8, 2, 16), jnp.float32)
        with amp.auto_cast(policy):
            out = ops.attention_reference(q, q, q)
        assert out.dtype == jnp.float32


class TestUnregisterBuiltinOverlap:
    def test_unregister_never_strips_builtin_surface(self):
        """Unregistering a user target that overlaps a BUILT-IN O1 entry
        reverts to the built-in treatment mid-scope (and unregistering a
        never-registered builtin is a no-op)."""
        from apex_tpu import amp
        policy = amp.Policy.from_opt_level("O1")
        a = jnp.ones((4, 4), jnp.float32)
        orig_mm = jnp.matmul
        with amp.auto_cast(policy):
            amp.register_half_op((jnp, "matmul"))
            amp.unregister_op((jnp, "matmul"))
            # built-in half surface must survive
            assert jnp.matmul(a, a).dtype == jnp.bfloat16
            # unregistering something never registered: no-op
            amp.unregister_op((jax.nn, "softmax"))
            s = jax.nn.softmax(a.astype(jnp.bfloat16))
            assert s.dtype == jnp.float32
        assert jnp.matmul is orig_mm
