"""apex_tpu.cluster — the shared-fs cluster control plane.

Generation-fenced membership and coordinated multi-rank recovery
(docs/resilience.md#control-plane) — the dynamic complement of
apexlint's APX201 static cross-rank congruence check. Three pieces:

- **membership & fencing** (:mod:`~apex_tpu.cluster.membership`):
  per-rank lease files (the heartbeat one-file-per-rank pattern, TTL'd
  so a crash needs no cleanup) plus a monotonic **generation** epoch
  committed manifest-last; :class:`ClusterMembership` is the ``fence=``
  object :class:`apex_tpu.ckpt.CheckpointManager` accepts — every
  checkpoint write/commit/delete validates its generation token against
  the committed epoch and a stale holder (a resumed zombie) is refused
  with a ``cluster_fence`` event before it can corrupt anything;
- **coordinated recovery** (:mod:`~apex_tpu.cluster.coordinator`):
  :class:`RecoveryCoordinator` turns
  :class:`~apex_tpu.guard.GuardPolicy`'s local rewind/escalate verdicts
  into cluster decisions — signed per-rank intents, deterministic
  resolution (oldest good step wins), a deadline-bounded barrier, and a
  generation bump fencing out stragglers of the old epoch;
  :class:`CollectiveDeadline` watches ``kind="collective"`` spans and
  distinguishes a hung collective from a slow one, feeding
  ``EscalationPolicy.trip("collective:...")``;
- **relaunch hygiene** (:func:`relaunch`): the ``elastic_run v2`` hook
  — bump the generation and garbage-collect stale lease/heartbeat
  files before a shrink-restart, so a dead rank's last heartbeat never
  reads as a "silent rank" of the new epoch.

Everything is host-side only: the ``cluster/no-extra-dispatch``
compile-check case pins that an instrumented step's compiled HLO is
bit-identical, donated and undonated. Events are JSONL on the cluster
channel (``MetricsLogger(cluster_sink=...)``, unbuffered — fencing
events must survive the crash they document;
``check_metrics_schema.py --kind cluster`` validates);
``scripts/cluster_audit.py --cpu8`` is the asserted scenario soak.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from apex_tpu.cluster.coordinator import (CollectiveDeadline,
                                          CoordinationError,
                                          RecoveryCoordinator,
                                          RecoveryDecision, intent_path)
from apex_tpu.cluster.membership import (GENERATION_PREFIX,
                                         INTENT_PREFIX,
                                         ClusterMembership, LeaseWriter,
                                         StaleGenerationError,
                                         bump_generation, cluster_token,
                                         gc_stale_intents,
                                         gc_stale_leases,
                                         generation_path, lease_path,
                                         mac_ok, read_generation,
                                         read_generation_record,
                                         read_leases)

__all__ = [
    "ClusterMembership", "LeaseWriter", "StaleGenerationError",
    "read_generation", "read_generation_record", "bump_generation",
    "read_leases", "lease_path", "gc_stale_leases", "gc_stale_intents",
    "mac_ok", "cluster_token", "GENERATION_PREFIX", "generation_path",
    "INTENT_PREFIX",
    "RecoveryCoordinator", "RecoveryDecision", "CoordinationError",
    "CollectiveDeadline", "intent_path",
    "relaunch",
]


def relaunch(directory: str, *, reason: str = "elastic_restart",
             rank: Optional[int] = None,
             heartbeat_dir: Optional[str] = None,
             event_sink: Optional[Callable[[Dict], None]] = None) -> int:
    """Fence and clean before a restart — the ``elastic_run v2`` hook.

    Bumps the committed generation (every straggler of the previous
    attempt now fails its fence checks instead of corrupting the new
    run) and garbage-collects lease files — and, when
    ``heartbeat_dir`` is given, straggler heartbeat files — left by
    older generations (a dead rank's last heartbeat otherwise reads as
    a "silent rank" forever). Returns the new generation.

    Idempotent *per restart*, not globally: each call opens a new
    epoch, which is exactly what a relaunch means.
    """
    member = ClusterMembership(directory, rank=rank,
                               event_sink=event_sink)
    member.join()
    new = member.bump(reason)
    member.gc_stale(heartbeat_dir=heartbeat_dir)
    # the relauncher is a controller, not a member: drop its transient
    # lease so the restarted ranks join a clean table (they re-acquire
    # their own leases under the new epoch)
    member.lease.release()
    return new
