"""Flat parameter arena: the substrate for fused optimizer kernels.

The reference's ``multi_tensor_apply`` machinery exists because CUDA kernels
cannot efficiently take a Python list of tensors: `multi_tensor_apply.cuh:
15-103` packs up to 110 tensor pointers into kernel-arg structs per launch.
On TPU the idiomatic equivalent is to *lay the tensors out flat*: one
contiguous 1-D buffer per dtype, each tensor in an aligned slot, so a single
Pallas kernel (or one fused XLA loop) updates every parameter with zero
per-tensor launch or marshalling overhead — and so ZeRO sharding is a pure
slice of the arena (`distributed_fused_adam.py:99-148` does the same with
128-byte aligned offsets).

The layout math (offsets/padding/buckets/shards) is computed by the native
planner (apex_tpu/csrc/arena_planner.cpp via ctypes, Python fallback); the
device-side gather/scatter is jitted XLA, fused into the surrounding step.

Usage::

    spec = arena.plan(params)                     # static layout
    flat = arena.flatten(params, spec)            # {dtype: 1-D buffer}
    params2 = arena.unflatten(flat, spec)         # exact round-trip
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.arena import native

# Default slot alignment in elements: 1024 = 8 sublanes x 128 lanes, so any
# slot start maps to a fp32 tile boundary when a buffer is viewed (-1, 128).
DEFAULT_ALIGNMENT = 1024

# Buffers are padded to a multiple of this so Pallas kernels can tile the
# (-1, 128) view into exact (512, 128) blocks with no remainder handling.
BUFFER_MULTIPLE = 512 * 128


@dataclasses.dataclass(frozen=True)
class _Partition:
    """Layout of one dtype's flat buffer (all entries static Python ints)."""
    dtype: str
    sizes: Tuple[int, ...]     # true element counts, leaf order
    offsets: Tuple[int, ...]   # aligned slot starts
    padded: Tuple[int, ...]    # aligned slot sizes
    total: int                 # sum of padded slot sizes
    buffer_len: int            # total rounded up to BUFFER_MULTIPLE


@dataclasses.dataclass(frozen=True)
class ArenaSpec:
    """Static arena layout. Hashable → safe to close over under jit."""
    treedef: Any
    leaf_shapes: Tuple[Tuple[int, ...], ...]
    leaf_dtypes: Tuple[str, ...]
    leaf_partition: Tuple[Tuple[str, int], ...]  # (dtype key, index in part.)
    partitions: Tuple[_Partition, ...]
    alignment: int

    def __hash__(self):
        return hash((self.treedef, self.leaf_shapes, self.leaf_dtypes,
                     self.alignment))

    @property
    def dtypes(self):
        return tuple(p.dtype for p in self.partitions)

    def partition(self, dtype) -> _Partition:
        key = str(jnp.dtype(dtype))
        for p in self.partitions:
            if p.dtype == key:
                return p
        raise KeyError(f"no arena partition for dtype {key}")

    @property
    def total_elements(self) -> int:
        return sum(p.total for p in self.partitions)


def plan(tree, alignment: int = DEFAULT_ALIGNMENT) -> ArenaSpec:
    """Compute the static arena layout for a pytree of arrays.

    Leaves are partitioned by dtype (the reference partitions its tensor
    lists the same way before each multi_tensor launch,
    `apex/optimizers/fused_adam.py:149-174`) and given aligned slots within
    their partition's flat buffer.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(x.shape) for x in leaves)
    dtypes = tuple(str(jnp.asarray(x).dtype) for x in leaves)

    by_dtype: Dict[str, list] = {}
    for i, dt in enumerate(dtypes):
        by_dtype.setdefault(dt, []).append(i)

    partitions = []
    leaf_partition: list = [None] * len(leaves)
    for dt in sorted(by_dtype):
        idxs = by_dtype[dt]
        sizes = np.array([int(np.prod(shapes[i])) if shapes[i] else 1
                          for i in idxs], np.int64)
        offsets, padded, total = native.plan_layout(sizes, alignment)
        buffer_len = -(-int(total) // BUFFER_MULTIPLE) * BUFFER_MULTIPLE
        partitions.append(_Partition(
            dtype=dt, sizes=tuple(int(s) for s in sizes),
            offsets=tuple(int(o) for o in offsets),
            padded=tuple(int(p) for p in padded), total=int(total),
            buffer_len=buffer_len))
        for j, i in enumerate(idxs):
            leaf_partition[i] = (dt, j)

    return ArenaSpec(treedef=treedef, leaf_shapes=shapes, leaf_dtypes=dtypes,
                     leaf_partition=tuple(leaf_partition),
                     partitions=tuple(partitions), alignment=alignment)


def flatten(tree, spec: ArenaSpec, cast=None) -> Dict[str, jax.Array]:
    """Pack a pytree into per-dtype flat buffers (jit-friendly).

    Padding elements are zero, so reductions over the raw buffer (l2 norms,
    finiteness checks) are safe without masking.

    ``cast`` re-types every buffer (e.g. ``cast=jnp.float32`` to flatten
    fp32 grads using the *param* tree's layout — buffers stay keyed by the
    partition's original dtype name so they line up slot-for-slot with the
    param buffers).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    assert len(leaves) == len(spec.leaf_shapes), "tree/spec mismatch"
    parts: Dict[str, list] = {p.dtype: [None] * len(p.sizes)
                              for p in spec.partitions}
    for leaf, (dt, j) in zip(leaves, spec.leaf_partition):
        part = spec.partition(dt)
        x = jnp.ravel(jnp.asarray(leaf))
        if cast is not None:
            x = x.astype(cast)
        pad = part.padded[j] - part.sizes[j]
        if pad:
            x = jnp.pad(x, (0, pad))
        parts[dt][j] = x
    out = {}
    for dt, chunks in parts.items():
        part = spec.partition(dt)
        buf_dtype = jnp.dtype(cast) if cast is not None else jnp.dtype(dt)
        buf = (jnp.concatenate(chunks) if chunks
               else jnp.zeros((0,), buf_dtype))
        if part.buffer_len > part.total:
            buf = jnp.pad(buf, (0, part.buffer_len - part.total))
        out[dt] = buf
    return out


def unflatten(buffers: Dict[str, jax.Array], spec: ArenaSpec):
    """Exact inverse of :func:`flatten`."""
    leaves = []
    for shape, dt_name, (dt, j) in zip(spec.leaf_shapes, spec.leaf_dtypes,
                                       spec.leaf_partition):
        part = spec.partition(dt)
        buf = buffers[dt]
        x = jax.lax.dynamic_slice_in_dim(buf, part.offsets[j], part.sizes[j])
        leaves.append(jnp.reshape(x, shape))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def zeros(spec: ArenaSpec, dtype=None) -> Dict[str, jax.Array]:
    """Fresh zeroed arena buffers (optimizer-state allocation).

    With ``dtype`` set, every partition's state buffer uses that dtype
    (e.g. fp32 master/momentum state for a bf16 param arena).
    """
    return {p.dtype: jnp.zeros((p.buffer_len,),
                               jnp.dtype(dtype) if dtype else jnp.dtype(p.dtype))
            for p in spec.partitions}


@functools.lru_cache(maxsize=128)
def segment_ids(spec: ArenaSpec, dtype) -> np.ndarray:
    """Host-side i32 map arena-position → tensor index (-1 in padding).

    Enables per-tensor reductions over the flat buffer in one pass
    (``jax.ops.segment_sum``) — how per-layer norms (NovoGrad, LAMB trust
    ratios) run without per-tensor kernel launches. Cached per (spec, dtype)
    — the map is a pure function of the static layout. Treat the result as
    read-only.
    """
    dtype = str(jnp.dtype(dtype))
    part = spec.partition(dtype)
    ids = np.full((part.buffer_len,), -1, np.int32)
    for j, (off, size) in enumerate(zip(part.offsets, part.sizes)):
        ids[off:off + size] = j
    ids.setflags(write=False)
    return ids


def segment_ids_device(spec: ArenaSpec, dtype) -> jax.Array:
    """Device-computed equivalent of :func:`segment_ids`.

    Embeds only the (num_tensors,) offset/size vectors in the program and
    derives the per-element map with a searchsorted over an iota — for big
    arenas this avoids materializing a buffer-sized host constant in the
    jitted step.
    """
    part = spec.partition(str(jnp.dtype(dtype)))
    starts = jnp.asarray(part.offsets, jnp.int32)
    sizes = jnp.asarray(part.sizes, jnp.int32)
    pos = jnp.arange(part.buffer_len, dtype=jnp.int32)
    ids = jnp.searchsorted(starts, pos, side="right").astype(jnp.int32) - 1
    valid = pos < (starts[ids] + sizes[ids])
    return jnp.where(valid, ids, -1)


def valid_mask(spec: ArenaSpec, dtype) -> np.ndarray:
    """Host-side bool mask of non-padding positions."""
    return segment_ids(spec, dtype) >= 0


def bucket_ids(spec: ArenaSpec, dtype, bucket_elems: int) -> np.ndarray:
    """Greedy message-size bucketing of a partition's slots (native planner).

    Kept for parity with DDP's ``message_size`` bucket tuning
    (`apex/parallel/distributed.py:363-394`); under XLA the same knob is the
    all-reduce combine threshold, but explicit buckets are used by the
    manual-overlap paths.
    """
    part = spec.partition(dtype)
    ids, _ = native.plan_buckets(np.array(part.padded, np.int64), bucket_elems)
    out = np.full((part.buffer_len,), -1, np.int32)
    for j, (off, size) in enumerate(zip(part.offsets, part.padded)):
        out[off:off + size] = int(ids[j])
    return out


def shard_pad(buffers: Dict[str, jax.Array], world_size: int,
              alignment: int = DEFAULT_ALIGNMENT):
    """Pad each buffer so its length divides evenly into ``world_size``
    aligned shards (ZeRO layout, `distributed_fused_adam.py:99-148`)."""
    out = {}
    for dt, buf in buffers.items():
        _, per = native.plan_shards(buf.shape[0], world_size, alignment)
        total = per * world_size
        if total > buf.shape[0]:
            buf = jnp.pad(buf, (0, total - buf.shape[0]))
        out[dt] = buf
    return out
