#!/usr/bin/env python
"""Dynamics audit: measure training dynamics, prove the estimators.

The asserting sibling of ``numerics_audit.py`` for the
training-dynamics axis (``run_tier1.sh --smoke`` runs it; exit status
is the verdict). Five claims, each printed and asserted:

(a) **GNS recovered within stated tolerance on known injected
    variance** — synthetic per-replica gradients ``g_i = mu + eps_i``
    with per-example noise ``N(0, sigma**2 I_d)`` averaged over a known
    per-replica batch, driven through the real pipeline (shard_map over
    8 virtual CPU devices, :func:`apex_tpu.parallel.distributed.
    dynamics_probe`'s registered collectives, the
    :func:`~apex_tpu.monitor.dynamics.dynamics_observe` fold): the
    reported ``B_simple`` matches the analytic
    ``d*sigma**2 / |mu|**2`` within 25%, and the intermediate
    ``G2``/``S`` estimators match their analytic values;
(b) **replica geometry reads right** — bit-replicated gradients
    measure cosine ≈ 1 and Adasum projection ≈ 1 at every replica; a
    seeded-decorrelation positive twin (noise-dominated per-replica
    gradients) drops the cosine spectrum to the analytic
    ``~1/sqrt(world)`` regime, strictly below the replicated run;
(c) **the convergence comparator flags at the right step** — a
    too-high-LR trajectory seeded to diverge at step 20 of a quadratic
    SGD run is flagged with ``first_flag_step`` in [20, 30] under a
    band calibrated from two paired-seed runs, while a third
    paired-seed twin passes clean;
(d) **O0–O3 observation parity** — the ``Amp.step(dynamics=…)`` hook
    leaves losses AND params bitwise identical with observation on vs
    off at every opt level (the same sweep tests/test_dynamics.py
    pins), with the expected fold count;
(e) **the stream validates and the step stays one program** — every
    event emitted by (a)–(c) passes ``check_metrics_schema.py --kind
    dynamics`` with all three kinds present, and the
    ``dynamics/no-extra-dispatch`` compile-check case (ONE executable,
    no host ops, HLO bit-identical donated+undonated) runs green.

Usage: python scripts/dynamics_audit.py --cpu8
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WORLD = 8
DIM = 4096
LOCAL_BATCH = 4
SIGMA = 0.3
GNS_FOLDS = 40
GNS_RTOL = 0.25


def _mu():
    import numpy as np
    rng = np.random.RandomState(11)
    return (rng.randn(DIM) * 0.05).astype("float32")


def _observe_step(mesh, cfg, mu_j):
    """The jitted shard_map'd observe step claims (a)/(b) share: each
    replica's gradient is ``mu + its noise row``, synced with a pmean,
    probed with the registered collectives, folded."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.monitor import dynamics as dx
    from apex_tpu.parallel import distributed as dist

    def inner(ds, noise):
        g_local = {"g": mu_j + noise[0]}
        g_bar = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "data"), g_local)
        probe = dist.dynamics_probe(g_local, g_bar, "data")
        return dx.dynamics_observe(
            ds, cfg, {"dynamics/update": g_bar}, probe=probe,
            grads={"dynamics/update": g_bar},
            weights={"dynamics/update": {"g": mu_j}})

    def step(ds, noise):
        return jax.shard_map(
            inner, mesh=mesh, in_specs=(P(), P("data")),
            out_specs=P(), check_vma=False)(ds, noise)

    return jax.jit(step)


def claim_a(logger):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from apex_tpu.monitor import dynamics as dx

    devs = jax.devices()
    assert len(devs) >= WORLD, (
        f"claim (a) needs {WORLD} devices (run with --cpu8), "
        f"got {len(devs)}")
    mesh = Mesh(np.array(devs[:WORLD]), ("data",))
    mu = _mu()
    true_g2 = float(np.sum(mu.astype("float64") ** 2))
    true_s = DIM * SIGMA ** 2            # per-example noise trace
    true_gns = true_s / true_g2

    cfg = dx.DynamicsConfig(check_every=1, ema=0.9,
                            local_batch=LOCAL_BATCH)
    sites = dx.site_names({"dynamics/update": {"g": mu}})
    ds = dx.dynamics_init(cfg, sites=sites, world=WORLD)
    jstep = _observe_step(mesh, cfg, jnp.asarray(mu))

    rng = np.random.RandomState(0)
    for _ in range(GNS_FOLDS):
        # a replica's gradient averages LOCAL_BATCH per-example noises:
        # per-coordinate std sigma/sqrt(b)
        noise = (rng.randn(WORLD, DIM)
                 * (SIGMA / np.sqrt(LOCAL_BATCH))).astype("float32")
        ds = jstep(ds, jnp.asarray(noise))
    rep = dx.dynamics_report(ds, sites, local_batch=LOCAL_BATCH)
    for ev in dx.check_events(ds, sites, local_batch=LOCAL_BATCH):
        logger.record_dynamics(ev)
    assert rep.world == WORLD, rep.world
    assert rep.gns is not None, "GNS undefined on a noisy run"
    rel = abs(rep.gns - true_gns) / true_gns
    assert rel <= GNS_RTOL, (
        f"GNS {rep.gns:.4g} vs injected {true_gns:.4g} "
        f"({rel:.1%} > {GNS_RTOL:.0%})")
    g2_rel = abs(rep.g2_est - true_g2) / true_g2
    s_rel = abs(rep.s_est - true_s) / true_s
    assert g2_rel <= GNS_RTOL, (rep.g2_est, true_g2)
    assert s_rel <= GNS_RTOL, (rep.s_est, true_s)
    # the companioned site gauges folded
    assert all(v is not None and v > 0 for v in rep.eff_lr)
    assert all(v is not None and v > 0 for v in rep.uw_ratio)
    print(f"  (a) GNS recovery ({WORLD} replicas x b={LOCAL_BATCH}, "
          f"d={DIM}, {GNS_FOLDS} folds): B_simple {rep.gns:.4g} vs "
          f"injected {true_gns:.4g} ({rel:.1%}); G2 {g2_rel:.1%}, "
          f"S {s_rel:.1%} off analytic (tolerance {GNS_RTOL:.0%})")
    return rep


def claim_b(logger):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from apex_tpu.monitor import dynamics as dx

    devs = jax.devices()
    mesh = Mesh(np.array(devs[:WORLD]), ("data",))
    mu = _mu()
    cfg = dx.DynamicsConfig(check_every=1, ema=0.9,
                            local_batch=LOCAL_BATCH)
    sites = dx.site_names({"dynamics/update": {"g": mu}})
    jstep = _observe_step(mesh, cfg, jnp.asarray(mu))

    # replicated: zero noise, every replica holds the same gradient
    ds_rep = jstep(dx.dynamics_init(cfg, sites=sites, world=WORLD),
                   jnp.zeros((WORLD, DIM), jnp.float32))
    rep = dx.dynamics_report(ds_rep, sites, local_batch=LOCAL_BATCH)
    assert rep.cos_min is not None and rep.cos_min > 0.9999, rep.cos_min
    assert max(abs(p - 1.0) for p in rep.proj_spectrum) < 1e-3, \
        rep.proj_spectrum
    for ev in dx.check_events(ds_rep, sites, local_batch=LOCAL_BATCH):
        logger.record_dynamics(ev)

    # seeded-decorrelation twin: noise dominates mu, so the per-replica
    # cosine against the pooled mean sits in the ~1/sqrt(world) regime
    rng = np.random.RandomState(5)
    noise = (rng.randn(WORLD, DIM) * 2.0).astype("float32")
    ds_dec = jstep(dx.dynamics_init(cfg, sites=sites, world=WORLD),
                   jnp.asarray(noise))
    dec = dx.dynamics_report(ds_dec, sites, local_batch=LOCAL_BATCH)
    assert dec.cos_mean < 0.6, dec.cos_mean
    assert dec.cos_min < rep.cos_min, (dec.cos_min, rep.cos_min)
    print(f"  (b) replica geometry: replicated grads measure "
          f"cos_min {rep.cos_min:.6f} / proj ≈ 1; decorrelated twin "
          f"drops to cos_mean {dec.cos_mean:.3f} "
          f"(~1/sqrt({WORLD}) = {1 / np.sqrt(WORLD):.3f})")


def _quadratic_sgd(seed, steps=60, lr=0.05, lr_switch=None,
                   lr_after=None):
    """A seeded noisy-SGD quadratic trajectory: fixed SPD curvature and
    init (the config), per-seed gradient noise (the 'data order'). The
    too-high-LR twin switches to ``lr_after`` at step ``lr_switch``,
    where ``1 - lr*lambda_max < -1`` makes the iterates oscillate and
    grow — a genuine divergence, not an injected constant."""
    import numpy as np
    rng_cfg = np.random.RandomState(123)
    d = 16
    q, _ = np.linalg.qr(rng_cfg.randn(d, d))
    lam = np.linspace(0.5, 4.0, d)
    a_mat = q @ np.diag(lam) @ q.T
    w = rng_cfg.randn(d)
    rng = np.random.RandomState(seed)
    losses = []
    for t in range(steps):
        cur = lr if lr_switch is None or t < lr_switch else lr_after
        g = a_mat @ w + rng.randn(d) * 0.01
        w = w - cur * g
        losses.append(float(0.5 * w @ a_mat @ w))
    return losses


def claim_c(logger):
    from apex_tpu.monitor.convergence import calibrate_band, \
        convergence_report

    # three calibration seeds -> three pairwise gap trajectories; the
    # grace window exempts the early transient, where the loss (and so
    # the seed-noise gap) is an order of magnitude above the bulk the
    # MAD measures — the same reason docs/dynamics.md#convergence says
    # to calibrate and compare over matching step ranges
    grace = 10
    cal_a = _quadratic_sgd(seed=1)
    band = calibrate_band([cal_a, _quadratic_sgd(seed=2),
                           _quadratic_sgd(seed=4)], z=8.0)

    # paired-seed twin: same config, unseen noise seed — must pass
    twin = _quadratic_sgd(seed=3)
    quiet = convergence_report(cal_a, twin, band=band, grace=grace)
    logger.record_dynamics(quiet.to_event())
    assert quiet.ok, quiet.summary()

    # too-high-LR run: identical to cal_a (same seed) until step 20,
    # then lr jumps past the 2/lambda_max stability bound
    switch = 20
    bad = _quadratic_sgd(seed=1, lr_switch=switch, lr_after=0.6)
    flagged = convergence_report(cal_a, bad, band=band, grace=grace)
    logger.record_dynamics(flagged.to_event())
    assert not flagged.ok, "divergent trajectory passed"
    assert flagged.first_flag_step is not None
    assert switch <= flagged.first_flag_step <= switch + 10, (
        f"flagged at step {flagged.first_flag_step}, divergence "
        f"seeded at {switch}")
    print(f"  (c) convergence comparator (band {band.threshold:.3g} "
          f"from {band.n_pairs} paired-seed pair(s)): too-high-LR "
          f"run flagged at step {flagged.first_flag_step} (seeded at "
          f"{switch}); paired-seed twin clean over {quiet.n_steps} "
          f"steps")


def _traj(opt_level, observe, steps=6):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import amp
    from apex_tpu.monitor import dynamics as dx
    from apex_tpu.optim import FusedLAMB

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16, 4).astype("float32")
                               * 0.1),
              "b": jnp.zeros((4,), jnp.float32)}
    x = jnp.asarray(rng.randn(8, 16).astype("float32"))
    y = jnp.asarray(rng.randn(8, 4).astype("float32"))
    amp_opt, state = amp.initialize(params, FusedLAMB(lr=1e-2),
                                    opt_level, verbosity=0)

    def loss_fn(mp, x, y):
        return jnp.mean(jnp.square(x @ mp["w"] + mp["b"] - y))

    dcfg = dx.DynamicsConfig(check_every=2)
    ds = dx.dynamics_init(dcfg,
                          sites=amp_opt.dynamics_sites(state.params))

    if observe:
        @jax.jit
        def step(state, ds, x, y):
            state, loss, fin, ds = amp_opt.step(
                state, loss_fn, x, y, dynamics=(ds, dcfg))
            return state, ds, loss
    else:
        @jax.jit
        def step(state, ds, x, y):
            state, loss, fin = amp_opt.step(state, loss_fn, x, y)
            return state, ds, loss

    losses = []
    for _ in range(steps):
        state, ds, loss = step(state, ds, x, y)
        losses.append(np.asarray(loss).tobytes())
    return losses, jax.device_get(state.params), ds


def claim_d():
    import numpy as np

    checked = []
    for opt_level in ("O0", "O1", "O2", "O3"):
        l_obs, p_obs, ds = _traj(opt_level, observe=True)
        l_ref, p_ref, _ = _traj(opt_level, observe=False)
        assert l_obs == l_ref, f"{opt_level}: losses differ observed " \
                               f"vs not"
        for k in p_ref:
            assert np.array_equal(np.asarray(p_obs[k]),
                                  np.asarray(p_ref[k])), \
                f"{opt_level}: params[{k}] differ observed vs not"
        n_checks = int(np.asarray(ds.check_count))
        assert n_checks == 3, (opt_level, n_checks)  # steps 0, 2, 4
        checked.append(opt_level)
    print(f"  (d) O0–O3 observation parity: losses AND params bitwise "
          f"identical with the dynamics fold on vs off at "
          f"{'/'.join(checked)} (3 folds per 6-step run)")


def claim_e(events_path):
    from apex_tpu.ops import compile_check as cc
    from scripts.check_metrics_schema import check_dynamics_lines

    with open(events_path) as f:
        errors = check_dynamics_lines(f)
    assert not errors, ("dynamics event schema violations:\n"
                        + "\n".join(errors))
    with open(events_path) as f:
        kinds = {json.loads(l)["kind"] for l in f if l.strip()}
    assert kinds == {"dynamics_check", "gns", "convergence_verdict"}, \
        kinds
    with open(events_path) as f:
        n = sum(1 for l in f if l.strip())
    assert cc.run(pattern="dynamics/no-extra-dispatch"), \
        "dynamics/no-extra-dispatch compile-check case failed"
    print(f"  (e) {n} dynamics events validate (--kind dynamics), all "
          f"three kinds present; dynamics/no-extra-dispatch "
          f"compile-check case green")


def main_audit():
    from apex_tpu import monitor

    tmp = tempfile.mkdtemp(prefix="apex_dynamics_audit_")
    events_path = os.path.join(tmp, "dynamics_events.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], dynamics_sink=monitor.JSONLSink(events_path))
    claim_a(logger)
    claim_b(logger)
    claim_c(logger)
    logger.close()
    claim_d()
    claim_e(events_path)
    print("dynamics audit ok")


def main():
    if "--cpu8" in sys.argv:
        import jax
        from apex_tpu import _compat
        jax.config.update("jax_platforms", "cpu")
        _compat.request_cpu_devices(8)
    main_audit()


if __name__ == "__main__":
    sys.exit(main())
