"""Op/module annotation for profiling — the pyprof.nvtx equivalent.

The reference monkey-patches ``torch.*`` / ``torch.nn.Module.forward`` to
emit NVTX ranges carrying op names and argument shapes
(`apex/pyprof/nvtx/nvmarker.py:1-222`). On TPU the idiomatic mechanisms
are:

- ``jax.named_scope`` — attaches a scope name to every HLO op traced under
  it, so the name survives into compiled XLA and shows up in xplane traces
  and HLO dumps (the in-graph analogue of an NVTX range);
- ``jax.profiler.TraceAnnotation`` — a host-side timeline range for
  un-jitted Python;
- a flax *interceptor* — the official extension point for wrapping every
  module method call, replacing the reference's forward-method
  monkey-patching with a scoped, reversible context.

``annotate_modules()`` records a :class:`CallRecord` (module path, method,
arg shapes/dtypes — the same payload nvmarker stringifies into its NVTX
marker) for every flax module call under the context and wraps each call
in a named scope, so per-module attribution appears in device traces.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Any, Callable, Iterator, List, Optional, Tuple

import jax


def scope(name: str):
    """In-graph scope: names every HLO op traced inside it.

    Usable as context manager or decorator (``jax.named_scope``
    semantics). Names nest with ``/`` separators and survive compilation,
    so xplane "XLA Ops" events and HLO dumps carry them.
    """
    return jax.named_scope(name)


def annotate(name: str) -> Callable:
    """Decorator: named_scope inside the graph + host TraceAnnotation.

    The host range shows trace/compile time spent in the function on the
    CPU timeline; the named scope attributes its compiled ops on the
    device timeline. Together these cover what a single NVTX range did in
    the reference (`apex/pyprof/nvtx/nvmarker.py:151-163`).

    Implemented over :class:`apex_tpu.trace.span`, so annotated
    functions additionally land in the active ``trace.Tracer`` step
    timeline (and flight-recorder dumps) whenever one is entered — the
    profiling and forensic annotation layers are the same spans.
    """

    def deco(fn: Callable) -> Callable:
        from apex_tpu.trace.spans import span as _span

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with _span(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco


def _shape_dtype(x: Any) -> Any:
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return (tuple(x.shape), str(x.dtype))
    if isinstance(x, (list, tuple)):
        return type(x)(_shape_dtype(v) for v in x)
    return repr(x)[:40]


@dataclasses.dataclass
class CallRecord:
    """One intercepted module call — the nvmarker payload, structured."""

    path: str            # flax module path, e.g. "ResNet/Dense_0"
    method: str          # method name, usually "__call__"
    args: Tuple[Any, ...]    # nested (shape, dtype) summaries
    kwargs: dict


@contextlib.contextmanager
def annotate_modules(records: Optional[List[CallRecord]] = None,
                     ) -> Iterator[List[CallRecord]]:
    """Record + scope every flax module call in the context.

    Yields the list the records accumulate into. Within the context each
    module method runs under ``named_scope("<path>.<method>")`` so device
    traces attribute ops per module (the reference's ``add_wrapper`` over
    ``Module.forward``, `apex/pyprof/nvtx/nvmarker.py:165-198`, without
    mutating any global state).

    Note: records are appended at *trace time*. Under ``jax.jit`` the
    function traces once and then runs from cache, so use this around the
    first (tracing) call, or on un-jitted applies.
    """
    import flax.linen as nn

    out: List[CallRecord] = [] if records is None else records

    def interceptor(next_fun, args, kwargs, context):
        path = "/".join(context.module.path) or type(context.module).__name__
        out.append(CallRecord(path=path, method=context.method_name,
                              args=_shape_dtype(args), kwargs={
                                  k: _shape_dtype(v)
                                  for k, v in kwargs.items()}))
        with jax.named_scope(f"{path}.{context.method_name}"):
            return next_fun(*args, **kwargs)

    with nn.intercept_methods(interceptor):
        yield out
