"""Fused (flash) attention vs the default impl.

Mirrors `apex/contrib/test/multihead_attn/*`: fast kernel outputs and
input grads match ``impl='default'`` within tolerance, for self/encdec,
additive masks, norm-add variants.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import ops
from apex_tpu.ops import attention as A


def rand_qkv(rng, b, s, h, d, sk=None):
    sk = sk or s
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, sk, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, sk, h, d).astype(np.float32))
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("s,d", [(64, 32), (128, 64), (200, 48)])
    def test_forward_matches_reference(self, s, d):
        rng = np.random.RandomState(0)
        q, k, v = rand_qkv(rng, 2, s, 2, d)
        got = A.flash_attention(q, k, v)
        ref = A.attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    def test_causal(self):
        rng = np.random.RandomState(1)
        q, k, v = rand_qkv(rng, 1, 96, 2, 32)
        got = A.flash_attention(q, k, v, causal=True)
        ref = A.attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    def test_additive_bias(self):
        rng = np.random.RandomState(2)
        q, k, v = rand_qkv(rng, 2, 64, 2, 32)
        # padding mask as additive bias on keys
        bias = jnp.where(jnp.arange(64)[None, None, None, :] < 48,
                         0.0, -1e9)
        got = A.flash_attention(q, k, v, bias=bias)
        ref = A.attention_reference(q, k, v, bias=bias)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    def test_cross_attention_lengths(self):
        rng = np.random.RandomState(3)
        q, k, v = rand_qkv(rng, 2, 40, 2, 32, sk=72)
        got = A.flash_attention(q, k, v)
        ref = A.attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward_matches_reference(self, causal):
        rng = np.random.RandomState(4)
        q, k, v = rand_qkv(rng, 2, 72, 2, 32)

        def lf(q_, k_, v_):
            return jnp.sum(jnp.sin(
                A.flash_attention(q_, k_, v_, causal=causal)))

        def lr(q_, k_, v_):
            return jnp.sum(jnp.sin(
                A.attention_reference(q_, k_, v_, causal=causal)))

        gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, e, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       atol=5e-5, err_msg=f"d{name}")

    def test_backward_with_bias(self):
        rng = np.random.RandomState(5)
        q, k, v = rand_qkv(rng, 1, 64, 2, 32)
        bias = jnp.where(jnp.arange(64)[None, None, None, :] < 50,
                         0.0, -1e9)

        gf = jax.grad(lambda q_: jnp.sum(
            A.flash_attention(q_, k, v, bias=bias)))(q)
        gr = jax.grad(lambda q_: jnp.sum(
            A.attention_reference(q_, k, v, bias=bias)))(q)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5)

    def test_bf16(self):
        rng = np.random.RandomState(6)
        q, k, v = rand_qkv(rng, 1, 64, 2, 32)
        q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
        got = A.flash_attention(q, k, v)
        assert got.dtype == jnp.bfloat16
        ref = A.attention_reference(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32),
            atol=2e-2)

    def test_long_sequence_blocks(self):
        """Multiple q and k blocks (S > block size) exercise the online
        renormalization."""
        rng = np.random.RandomState(7)
        q, k, v = rand_qkv(rng, 1, 384, 1, 32)
        got = A.flash_attention(q, k, v, block_q=128, block_k=128)
        ref = A.attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)


class TestMHAModules:
    @pytest.mark.parametrize("norm_add", [False, True])
    def test_self_attn_fast_vs_default(self, norm_add):
        rng = np.random.RandomState(8)
        x = jnp.asarray(rng.randn(2, 48, 64).astype(np.float32))
        fast = ops.SelfMultiheadAttn(64, 4, impl="fast",
                                     include_norm_add=norm_add)
        slow = ops.SelfMultiheadAttn(64, 4, impl="default",
                                     include_norm_add=norm_add)
        variables = fast.init(jax.random.PRNGKey(0), x)
        yf = fast.apply(variables, x)
        ys = slow.apply(variables, x)
        np.testing.assert_allclose(np.asarray(yf), np.asarray(ys),
                                   atol=2e-4)

    def test_self_attn_separate_qkv(self):
        rng = np.random.RandomState(9)
        x = jnp.asarray(rng.randn(1, 32, 32).astype(np.float32))
        m = ops.SelfMultiheadAttn(32, 2, separate_qkv_params=True)
        variables = m.init(jax.random.PRNGKey(0), x)
        names = set(variables["params"].keys())
        assert {"q_proj", "k_proj", "v_proj", "out_proj"} <= names
        assert m.apply(variables, x).shape == x.shape

    def test_encdec_fast_vs_default(self):
        rng = np.random.RandomState(10)
        q = jnp.asarray(rng.randn(2, 24, 64).astype(np.float32))
        mem = jnp.asarray(rng.randn(2, 56, 64).astype(np.float32))
        fast = ops.EncdecMultiheadAttn(64, 4, impl="fast")
        slow = ops.EncdecMultiheadAttn(64, 4, impl="default")
        variables = fast.init(jax.random.PRNGKey(0), q, mem)
        yf = fast.apply(variables, q, mem)
        ys = slow.apply(variables, q, mem)
        np.testing.assert_allclose(np.asarray(yf), np.asarray(ys),
                                   atol=2e-4)

    def test_grad_through_module(self):
        rng = np.random.RandomState(11)
        x = jnp.asarray(rng.randn(1, 32, 32).astype(np.float32))
        m = ops.SelfMultiheadAttn(32, 2, impl="fast")
        variables = m.init(jax.random.PRNGKey(0), x)

        g = jax.grad(lambda v: jnp.sum(m.apply(v, x) ** 2))(variables)
        leaves = jax.tree_util.tree_leaves(g)
        assert all(bool(jnp.all(jnp.isfinite(l))) for l in leaves)
        assert any(float(jnp.max(jnp.abs(l))) > 0 for l in leaves)

    def test_mask_softmax_dropout(self):
        rng = np.random.RandomState(12)
        s = jnp.asarray(rng.randn(2, 4, 16, 16).astype(np.float32))
        mask = jnp.asarray(rng.rand(2, 1, 16, 16) > 0.3)
        p = ops.mask_softmax_dropout(s, mask)
        sums = np.asarray(jnp.sum(p, axis=-1))
        np.testing.assert_allclose(sums, 1.0, atol=1e-5)
        assert bool(jnp.all(jnp.where(~mask, p == 0, True)))


class TestCausalCrossLength:
    def test_causal_cross_attention_alignment(self):
        """Bottom-right causal alignment for Sq != Sk (decode-style)."""
        rng = np.random.RandomState(13)
        q, k, v = rand_qkv(rng, 1, 8, 2, 32, sk=16)
        got = A.flash_attention(q, k, v, causal=True)
        ref = A.attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    def test_causal_cross_backward(self):
        rng = np.random.RandomState(14)
        q, k, v = rand_qkv(rng, 1, 24, 2, 32, sk=40)
        gf = jax.grad(lambda k_: jnp.sum(
            A.flash_attention(q, k_, v, causal=True) ** 2))(k)
        gr = jax.grad(lambda k_: jnp.sum(
            A.attention_reference(q, k_, v, causal=True) ** 2))(k)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5)


class TestSoftmaxDropout:
    def test_single_softmax_dropout(self):
        """Dropout applies ONCE, to the probabilities (reference
        semantics) — mean output magnitude stays unbiased."""
        rng = np.random.RandomState(15)
        x = jnp.asarray(rng.randn(2, 32, 64).astype(np.float32))
        m = ops.SelfMultiheadAttn(64, 4, dropout=0.5, impl="fast")
        variables = m.init(jax.random.PRNGKey(0), x)
        y_det = m.apply(variables, x, deterministic=True)
        y_drop = m.apply(variables, x, deterministic=False,
                         rngs={"dropout": jax.random.PRNGKey(1)})
        # dropped path differs but is unbiased: mean ratio near 1
        assert not np.allclose(np.asarray(y_det), np.asarray(y_drop))
        r = float(jnp.mean(jnp.abs(y_drop)) / jnp.mean(jnp.abs(y_det)))
        assert 0.5 < r < 2.0


class TestBiasGradient:
    """Learned-bias cotangent (ADVICE round-1 #4): d/dbias of the fused
    path must match the jnp oracle — relative-position-bias training."""

    @pytest.mark.parametrize("bias_shape", [
        (1, 1, 64, 64),   # shared (ring-attention causal-offset shape)
        (1, 2, 64, 64),   # per-head (relative position bias)
        (2, 1, 64, 64),   # per-batch mask
        (2, 2, 64, 64),   # full
    ])
    def test_dbias_matches_reference(self, bias_shape):
        rng = np.random.RandomState(7)
        q, k, v = rand_qkv(rng, 2, 64, 2, 32)
        bias = jnp.asarray(rng.randn(*bias_shape).astype(np.float32))

        gf = jax.grad(lambda b_: jnp.sum(
            A.flash_attention(q, k, v, bias=b_)), )(bias)
        gr = jax.grad(lambda b_: jnp.sum(
            A.attention_reference(q, k, v, bias=b_)))(bias)
        assert gf.shape == bias.shape
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5)

    def test_dbias_causal(self):
        rng = np.random.RandomState(8)
        q, k, v = rand_qkv(rng, 1, 48, 2, 32)
        bias = jnp.asarray(rng.randn(1, 2, 48, 48).astype(np.float32))
        gf = jax.grad(lambda b_: jnp.sum(
            A.flash_attention(q, k, v, bias=b_, causal=True)))(bias)
        gr = jax.grad(lambda b_: jnp.sum(
            A.attention_reference(q, k, v, bias=b_, causal=True)))(bias)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5)

    def test_broadcast_bias_not_materialized(self):
        """The (1,1,S,S) bias must flow to the kernel ungrown — assert the
        jaxpr contains no (B*H, S, S)-sized broadcast of it."""
        # s must differ from the padded head dim (128) or the q/k/v
        # d-padding pad op's (B*H, S, 128) shape collides with the
        # (B*H, S, S) pattern this test greps for
        b, s, h, d = 4, 256, 4, 32
        rng = np.random.RandomState(9)
        q, k, v = rand_qkv(rng, b, s, h, d)
        bias = jnp.zeros((1, 1, s, s), jnp.float32)
        jaxpr = jax.make_jaxpr(
            lambda q_, k_, v_, b_: A.flash_attention(q_, k_, v_, bias=b_)
        )(q, k, v, bias)
        blown_up = f"{b * h},{s},{s}"
        assert blown_up not in str(jaxpr).replace(" ", ""), \
            "bias was broadcast to B*H copies before the kernel"


class TestFusedDropout:
    """In-kernel softmax dropout (the reference's fused Philox dropout,
    `apex/contrib/csrc/multihead_attn/dropout.h:1-308`). The mask is
    counter-based, so a dense jnp replica (`_keep_mask_dense`) lets us
    compare the kernel against an exact oracle — forward AND gradients."""

    def _oracle(self, q, k, v, seed, rate, bias=None, causal=False):
        """Reference attention applying the *same* mask the kernel
        generates, via the dense mask replica."""
        b, sq, h, d = q.shape
        sk = k.shape[1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / np.sqrt(d)
        if bias is not None:
            s = s + bias.astype(jnp.float32)
        if causal:
            cm = np.tril(np.ones((sq, sk), bool), k=sk - sq)
            s = jnp.where(cm, s, A.NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        # apply the same tile cap the kernels use (shared definition —
        # the dropout mask is a function of block coordinates)
        cq, ck = A._block_cap(A.DEFAULT_BLOCK_Q, A.DEFAULT_BLOCK_K,
                              False, rate)
        bq = A._choose_block(cq, sq)
        bk = A._choose_block(ck, sk, lane=True)
        keep = A._keep_mask_dense(jnp.asarray(seed, jnp.int32), b, h,
                                  sq, sk, bq, bk, rate)
        keep = keep.reshape(b, h, sq, sk)
        pt = jnp.where(keep, p / (1.0 - rate), 0.0)
        return jnp.einsum("bhqk,bkhd->bqhd", pt,
                          v.astype(jnp.float32)).astype(q.dtype)

    def test_forward_matches_masked_oracle(self):
        rng = np.random.RandomState(3)
        q, k, v = rand_qkv(rng, 2, 192, 2, 32)
        got = A.flash_attention(q, k, v, dropout_rate=0.25,
                                dropout_seed=7)
        ref = self._oracle(q, k, v, 7, 0.25)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    def test_grads_match_masked_oracle(self):
        rng = np.random.RandomState(4)
        q, k, v = rand_qkv(rng, 1, 128, 2, 32)

        def loss_fused(q_, k_, v_):
            o = A.flash_attention(q_, k_, v_, dropout_rate=0.3,
                                  dropout_seed=11)
            return jnp.sum(o * o)

        def loss_ref(q_, k_, v_):
            o = self._oracle(q_, k_, v_, 11, 0.3)
            return jnp.sum(o * o)

        gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5)

    def test_dbias_with_dropout(self):
        rng = np.random.RandomState(5)
        q, k, v = rand_qkv(rng, 1, 64, 2, 32)
        bias = jnp.asarray(rng.randn(1, 2, 64, 64).astype(np.float32))
        gf = jax.grad(lambda b_: jnp.sum(A.flash_attention(
            q, k, v, bias=b_, dropout_rate=0.2, dropout_seed=13)))(bias)
        gr = jax.grad(lambda b_: jnp.sum(self._oracle(
            q, k, v, 13, 0.2, bias=b_)))(bias)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5)

    def test_keep_rate_statistics(self):
        """Uniform scores (q=0) make every prob 1/S, so with v=1 the
        output row-sum directly reads off the kept fraction."""
        b, s, h, d = 2, 256, 2, 32
        rate = 0.3
        q = jnp.zeros((b, s, h, d), jnp.float32)
        k = jnp.zeros((b, s, h, d), jnp.float32)
        v = jnp.ones((b, s, h, d), jnp.float32)
        out = A.flash_attention(q, k, v, dropout_rate=rate,
                                dropout_seed=99)
        # out = kept_count / (S * keep_prob); recover mean keep fraction
        keep_frac = float(jnp.mean(out)) * (1.0 - rate)
        n = b * h * s * s
        sigma = np.sqrt(rate * (1 - rate) / n)
        assert abs(keep_frac - (1.0 - rate)) < 5 * sigma, \
            f"keep fraction {keep_frac} vs expected {1 - rate}"

    def test_seed_determinism(self):
        rng = np.random.RandomState(6)
        q, k, v = rand_qkv(rng, 1, 64, 2, 32)
        a1 = A.flash_attention(q, k, v, dropout_rate=0.5, dropout_seed=1)
        a2 = A.flash_attention(q, k, v, dropout_rate=0.5, dropout_seed=1)
        b2 = A.flash_attention(q, k, v, dropout_rate=0.5, dropout_seed=2)
        assert bool(jnp.all(a1 == a2)), "same seed must be bitwise equal"
        assert not bool(jnp.all(a1 == b2)), "different seeds must differ"

    def test_module_keeps_fused_path_under_dropout(self):
        """Training with dropout>0 must NOT fall back to the O(S²) jnp
        path — the jaxpr of the training forward contains the kernel."""
        x = jnp.zeros((2, 64, 64), jnp.float32)
        m = ops.SelfMultiheadAttn(64, 4, dropout=0.1, impl="fast")
        variables = m.init(jax.random.PRNGKey(0), x)
        jaxpr = jax.make_jaxpr(lambda v_, x_: m.apply(
            v_, x_, deterministic=False,
            rngs={"dropout": jax.random.PRNGKey(1)}))(variables, x)
        assert "pallas_call" in str(jaxpr), \
            "fused kernel not used in training forward with dropout"

    def test_missing_seed_raises(self):
        rng = np.random.RandomState(7)
        q, k, v = rand_qkv(rng, 1, 32, 1, 32)
        with pytest.raises(ValueError, match="dropout_seed"):
            A.flash_attention(q, k, v, dropout_rate=0.5)


class TestNativeLayoutPath:
    """d=64-class shapes route through the native-layout kernels
    (heads sliced from the lane axis — see the native-kernel block in
    ops/attention.py); these pin the fwd, both bwd variants (fused
    single-sweep and two-kernel multi-block) and the dropout
    coordinate reconstruction against the same oracles the transposed
    path is held to. d=32/d=16 tests elsewhere cover the transposed
    fallback."""

    def _grads(self, fn, args, argn=(0, 1, 2)):
        return jax.jit(jax.grad(
            lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2),
            argnums=argn))(*args)

    @pytest.mark.parametrize("s,causal", [(128, False), (384, True)])
    def test_fused_single_sweep_bwd_matches_oracle(self, s, causal):
        # single-block grid -> the fused dq/dk/dv sweep
        rng = np.random.RandomState(5)
        q, k, v = rand_qkv(rng, 2, s, 4, 64)
        assert A._native_g0(4, 64) == 2

        def fn(q, k, v):
            return A.flash_attention(q, k, v, causal=causal)

        def ref(q, k, v):
            return A.attention_reference(q, k, v, causal=causal)

        np.testing.assert_allclose(jax.jit(fn)(q, k, v), ref(q, k, v),
                                   atol=2e-5, rtol=1e-5)
        for g, w in zip(self._grads(fn, (q, k, v)),
                        self._grads(ref, (q, k, v))):
            np.testing.assert_allclose(g, w, atol=5e-4, rtol=1e-3)

    def test_two_kernel_multiblock_bwd_matches_oracle(self):
        # force a multi-block grid (block_q/k < s) -> two-kernel path
        rng = np.random.RandomState(6)
        q, k, v = rand_qkv(rng, 1, 256, 4, 64)

        def fn(q, k, v):
            return A.flash_attention(q, k, v, causal=True, block_q=128,
                                     block_k=128)

        def ref(q, k, v):
            return A.attention_reference(q, k, v, causal=True)

        np.testing.assert_allclose(jax.jit(fn)(q, k, v), ref(q, k, v),
                                   atol=2e-5, rtol=1e-5)
        for g, w in zip(self._grads(fn, (q, k, v)),
                        self._grads(ref, (q, k, v))):
            np.testing.assert_allclose(g, w, atol=5e-4, rtol=1e-3)

    @pytest.mark.parametrize("s,bq", [(128, None), (256, 128)])
    def test_native_dropout_matches_dense_mask_oracle(self, s, bq):
        """gb = t·g + h must reproduce the dense replica's bh-row
        numbering — fwd values AND gradients, single- and multi-block."""
        rng = np.random.RandomState(7)
        q, k, v = rand_qkv(rng, 1, s, 4, 64)
        rate, seed = 0.3, 17
        kw = {} if bq is None else {"block_q": bq, "block_k": bq}

        def fn(q, k, v):
            return A.flash_attention(q, k, v, dropout_rate=rate,
                                     dropout_seed=seed, **kw)

        cq, ck = A._block_cap(kw.get("block_q", A.DEFAULT_BLOCK_Q),
                              kw.get("block_k", A.DEFAULT_BLOCK_K),
                              False, rate)
        bq_ = A._choose_block(cq, s)
        bk_ = A._choose_block(ck, s, lane=True)

        def ref(q, k, v):
            b, sq, h, d = q.shape
            sm = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
            p = jax.nn.softmax(sm, axis=-1)
            keep = A._keep_mask_dense(jnp.asarray(seed, jnp.int32), b,
                                      h, sq, sq, bq_, bk_, rate)
            pd = jnp.where(keep.reshape(b, h, sq, sq), p / (1 - rate),
                           0.0)
            return jnp.einsum("bhqk,bkhd->bqhd", pd, v)

        np.testing.assert_allclose(jax.jit(fn)(q, k, v), ref(q, k, v),
                                   atol=2e-5, rtol=1e-5)
        for g, w in zip(self._grads(fn, (q, k, v)),
                        self._grads(ref, (q, k, v))):
            np.testing.assert_allclose(g, w, atol=5e-4, rtol=1e-3)


class TestCausalOffset:
    """flash_attention(causal_offset=...) vs the additive-mask oracle:
    the offset (a traced scalar) must reproduce exactly the mask a
    caller would build — native path (d=64) and bias-fallback path
    (d=32), lse variant included (the ring-hop building block)."""

    @pytest.mark.parametrize("d", [64, 32])
    @pytest.mark.parametrize("off", [0, 64, 4096])
    def test_matches_offset_bias_oracle(self, d, off):
        rng = np.random.RandomState(11)
        q, k, v = rand_qkv(rng, 1, 128, 4, d)

        def fn(q, k, v, off_):
            return A.flash_attention(q, k, v, causal=True,
                                     causal_offset=off_)

        rows = np.arange(128)[:, None] + off
        cols = np.arange(128)[None, :]
        bias = jnp.asarray(np.where(rows >= cols, 0.0, A.NEG_INF),
                           jnp.float32)[None, None]

        def ref(q, k, v):
            return A.attention_reference(q, k, v, bias=bias)

        got = jax.jit(fn)(q, k, v, jnp.int32(off))
        np.testing.assert_allclose(got, ref(q, k, v), atol=2e-5,
                                   rtol=1e-5)
        g1 = jax.jit(jax.grad(
            lambda q, k, v, o_: jnp.sum(fn(q, k, v, o_) ** 2),
            argnums=(0, 1, 2)))(q, k, v, jnp.int32(off))
        g2 = jax.grad(lambda q, k, v: jnp.sum(ref(q, k, v) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)

    def test_fully_masked_rows_finite_and_lse_guarded(self):
        """Negative offsets can leave early rows with NO valid keys.
        Those rows are out-of-contract (softmax over an empty set);
        what the framework guarantees is (a) finite outputs/gradients
        and (b) an lse of ~NEG_INF so ring attention's merge gives the
        hop zero weight — the guard `ring.py` relies on. Valid rows
        must still match the oracle exactly."""
        rng = np.random.RandomState(14)
        q, k, v = rand_qkv(rng, 1, 128, 2, 64)
        off = -96   # rows 0..95 fully masked
        o, lse = jax.jit(lambda q, k, v: A.flash_attention_lse(
            q, k, v, causal=True,
            causal_offset=jnp.int32(off)))(q, k, v)
        assert np.all(np.isfinite(np.asarray(o, np.float32)))
        # masked rows: merge weight exp(lse - lse_c) underflows to 0
        assert np.all(np.asarray(lse)[..., :96] < -1e29)
        assert np.all(np.asarray(lse)[..., 96:] > -1e4)
        # valid rows agree with the dense oracle
        rows = np.arange(128)[:, None] + off
        cols = np.arange(128)[None, :]
        bias = jnp.asarray(np.where(rows >= cols, 0.0, A.NEG_INF),
                           jnp.float32)[None, None]
        want = A.attention_reference(q, k, v, bias=bias)
        np.testing.assert_allclose(np.asarray(o)[:, 96:],
                                   np.asarray(want)[:, 96:], atol=2e-5,
                                   rtol=1e-5)
        g = jax.jit(jax.grad(lambda q: jnp.sum(A.flash_attention(
            q, k, v, causal=True,
            causal_offset=jnp.int32(off)) ** 2)))(q)
        assert np.all(np.isfinite(np.asarray(g, np.float32)))

    def test_lse_variant_offset(self):
        rng = np.random.RandomState(12)
        q, k, v = rand_qkv(rng, 1, 128, 2, 64)
        o1, lse1 = A.flash_attention_lse(q, k, v, causal=True,
                                         causal_offset=jnp.int32(32))
        rows = np.arange(128)[:, None] + 32
        cols = np.arange(128)[None, :]
        bias = jnp.asarray(np.where(rows >= cols, 0.0, A.NEG_INF),
                           jnp.float32)[None, None]
        o2, lse2 = A.flash_attention_lse(q, k, v, bias=bias)
        np.testing.assert_allclose(o1, o2, atol=2e-5, rtol=1e-5)
        np.testing.assert_allclose(lse1, lse2, atol=1e-5, rtol=1e-5)

    def test_offset_requires_causal(self):
        rng = np.random.RandomState(13)
        q, k, v = rand_qkv(rng, 1, 64, 2, 64)
        with pytest.raises(ValueError):
            A.flash_attention(q, k, v, causal_offset=jnp.int32(1))

    @pytest.mark.parametrize("off", [0, 96])
    def test_multiblock_native_offset_bwd(self, off):
        """Small blocks over S=256 force the two-kernel native backward
        (the kernels a ring hop at per-shard S > the tile hits): the
        off_ref handling in _bwd_dq_kernel_nl/_bwd_dkv_kernel_nl must
        match the dense oracle, gradients included."""
        rng = np.random.RandomState(15)
        q, k, v = rand_qkv(rng, 1, 256, 2, 64)
        kw = {"block_q": 128, "block_k": 128}

        def fn(q, k, v, off_):
            return A.flash_attention(q, k, v, causal=True,
                                     causal_offset=off_, **kw)

        rows = np.arange(256)[:, None] + off
        cols = np.arange(256)[None, :]
        bias = jnp.asarray(np.where(rows >= cols, 0.0, A.NEG_INF),
                           jnp.float32)[None, None]

        def ref(q, k, v):
            return A.attention_reference(q, k, v, bias=bias)

        got = jax.jit(fn)(q, k, v, jnp.int32(off))
        np.testing.assert_allclose(got, ref(q, k, v), atol=2e-5,
                                   rtol=1e-5)
        g1 = jax.jit(jax.grad(
            lambda q, k, v, o_: jnp.sum(fn(q, k, v, o_) ** 2),
            argnums=(0, 1, 2)))(q, k, v, jnp.int32(off))
        g2 = jax.grad(lambda q, k, v: jnp.sum(ref(q, k, v) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_lse_variant_bias_cotangent():
    """flash_attention_lse returns a bias gradient that folds the lse
    cotangent (ds = p*(dp - (delta - dlse))) — round-5; previously the
    bias slot was silently None."""
    from apex_tpu.ops.attention import flash_attention_lse

    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, 128, 2, 64), jnp.float32)
               for _ in range(3))
    bias = jnp.asarray(rng.randn(1, 2, 128, 128), jnp.float32) * 0.3

    def loss(bias):
        o, lse = flash_attention_lse(q, k, v, bias)
        # lse term makes dlse nonzero, exercising the shift fold
        return jnp.sum(jnp.sin(o)) + jnp.sum(lse * 0.01)

    def loss_ref(bias):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(64) + bias
        p = jax.nn.softmax(s, -1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        lse = jax.scipy.special.logsumexp(s, -1)
        return jnp.sum(jnp.sin(o)) + jnp.sum(lse * 0.01)

    with jax.default_matmul_precision("highest"):
        db = jax.jit(jax.grad(loss))(bias)
        db_ref = jax.jit(jax.grad(loss_ref))(bias)
    np.testing.assert_allclose(np.asarray(db), np.asarray(db_ref),
                               atol=2e-4)
