"""apexlint — jaxpr/HLO static-analysis pass suite.

One seeded-violation fixture per rule (a small jaxpr / HLO module that
triggers exactly its rule) plus a negative twin that must NOT fire —
the per-rule contract ISSUE 5 demands — and the integration claims:

- the donation rule's wasted-bytes estimate for the PRE-fix
  ``prof_bert.py``-structure step (undonated) agrees with
  ``prof.memory_report``'s params+optimizer_state attribution within
  5%, and the donated twin lints clean;
- the post-fix flagship-structure steps produce zero error-severity
  findings (the no-false-positive guard behind the
  ``run_tier1.sh --smoke`` gate);
- Report plumbing: baseline suppression round-trip, lint JSONL events
  through ``MetricsLogger(lint_sink=...)`` validating under
  ``check_metrics_schema.py --kind lint`` (in-process and subprocess);
- the two ``lint/*`` compile-check cases run as registered.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, lint, models, monitor, prof
from apex_tpu.lint import findings as F
from apex_tpu.optim import FusedSGD

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_SCHEMA_SCRIPT = os.path.join(_REPO_ROOT, "scripts",
                              "check_metrics_schema.py")


def _rules(findings):
    return sorted({f.rule for f in findings})


# --- jaxpr pass: seeded violation + negative twin per rule -------------------

class TestRngKeyReuse:
    def test_fires_on_raw_key_reuse(self):
        def f(key, x):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b + x

        fs = lint.lint_jaxpr(f, jax.random.PRNGKey(0), jnp.zeros(4))
        hits = [f_ for f_ in fs if f_.rule == "rng-key-reuse"]
        assert len(hits) == 1 and hits[0].count == 2
        assert hits[0].severity == "error"

    def test_fires_on_typed_key_reuse(self):
        def f(key, x):
            return (jax.random.normal(key, (4,))
                    + jax.random.uniform(key, (4,)) + x)

        fs = lint.lint_jaxpr(f, jax.random.key(0), jnp.zeros(4))
        assert "rng-key-reuse" in _rules(fs)

    def test_split_then_use_is_reuse(self):
        # splitting a key and ALSO drawing from it is the classic bug
        def f(key):
            k1, _ = jax.random.split(key)
            return jax.random.normal(key, (2,)) + jax.random.normal(
                k1, (2,))

        assert "rng-key-reuse" in _rules(
            lint.lint_jaxpr(f, jax.random.PRNGKey(0)))

    def test_clean_split_does_not_fire(self):
        def f(key, x):
            k1, k2 = jax.random.split(key)
            return (jax.random.normal(k1, (4,))
                    + jax.random.uniform(k2, (4,)) + x)

        assert "rng-key-reuse" not in _rules(
            lint.lint_jaxpr(f, jax.random.PRNGKey(0), jnp.zeros(4)))


class TestF64Creep:
    def test_fires_on_f64(self):
        from jax.experimental import enable_x64
        with enable_x64():
            fs = lint.lint_jaxpr(
                lambda x: jnp.sum(x.astype(jnp.float64)),
                jnp.zeros(4, jnp.float32))
        hits = [f for f in fs if f.rule == "f64-creep"]
        assert len(hits) == 1 and hits[0].severity == "error"
        assert hits[0].count >= 1

    def test_clean_f32_does_not_fire(self):
        fs = lint.lint_jaxpr(lambda x: jnp.sum(x * 2), jnp.zeros(4))
        assert "f64-creep" not in _rules(fs)


class TestFp32MatmulInAmp:
    def test_fires_under_half_policy(self):
        pol = amp.Policy.from_opt_level("O2")

        def mm(a, b):
            return a @ b

        fs = lint.lint_jaxpr(mm, jnp.zeros((8, 128)),
                             jnp.zeros((128, 128)), policy=pol)
        hits = [f for f in fs if f.rule == "fp32-matmul-in-amp"]
        assert len(hits) == 1 and hits[0].severity == "warning"

    def test_bf16_matmul_does_not_fire(self):
        pol = amp.Policy.from_opt_level("O2")

        def mm(a, b):
            return a @ b

        fs = lint.lint_jaxpr(
            mm, jnp.zeros((8, 128), jnp.bfloat16),
            jnp.zeros((128, 128), jnp.bfloat16), policy=pol)
        assert "fp32-matmul-in-amp" not in _rules(fs)

    def test_inactive_without_policy(self):
        def mm(a, b):
            return a @ b

        fs = lint.lint_jaxpr(mm, jnp.zeros((8, 128)),
                             jnp.zeros((128, 128)))
        assert "fp32-matmul-in-amp" not in _rules(fs)


class TestHostCallback:
    def test_fires_on_debug_print(self):
        def f(x):
            jax.debug.print("x={x}", x=x.sum())
            return x * 2

        fs = lint.lint_jaxpr(f, jnp.ones(4))
        hits = [f_ for f_ in fs if f_.rule == "host-callback-in-step"]
        assert len(hits) == 1 and hits[0].severity == "error"
        assert hits[0].op == "debug_callback"

    def test_clean_step_does_not_fire(self):
        fs = lint.lint_jaxpr(lambda x: x * 2, jnp.ones(4))
        assert fs == []


# --- HLO pass: seeded violation + negative twin per rule ---------------------

def _toy_amp_step():
    """Small Amp O2 train step with real params/opt-state arg paths."""
    pol = amp.Policy.from_opt_level("O2")
    params = {"w": jnp.zeros((64, 64), jnp.float32),
              "b": jnp.zeros((64,), jnp.float32)}
    amp_opt = amp.Amp(pol, FusedSGD(lr=0.1, momentum=0.9))
    state = amp_opt.init(params)
    x = jnp.zeros((8, 64))
    y = jnp.zeros((8, 64))

    def step(state, x, y):
        def loss_fn(mp):
            return jnp.mean((x @ mp["w"] + mp["b"] - y) ** 2)
        loss, grads, state, finite = amp_opt.backward(state, loss_fn)
        return amp_opt.apply_gradients(state, grads, finite), loss

    return step, state, x, y, pol


class TestDonationMiss:
    def test_fires_on_undonated_step(self):
        step, state, x, y, pol = _toy_amp_step()
        rep = lint.lint_step(jax.jit(step), state, x, y, policy=pol)
        hits = rep.by_rule("donation-miss")
        assert hits and all(h.severity == "error" for h in hits)
        # evidence: arg paths name the carried state, bytes estimated
        assert any("opt_state" in (h.scope or "") for h in hits)
        assert all((h.bytes or 0) > 0 for h in hits)

    def test_donated_step_is_clean(self):
        step, state, x, y, pol = _toy_amp_step()
        rep = lint.lint_step(jax.jit(step, donate_argnums=(0,)),
                             state, x, y, policy=pol)
        assert rep.by_rule("donation-miss") == []
        assert rep.errors == []

    def test_inference_params_not_flagged(self):
        # params that never come back out have no output to donate
        # into — not carried state, not a finding
        params = {"w": jnp.zeros((64, 64)), "b": jnp.zeros((64,))}

        def infer(params, x):
            return x @ params["w"] + params["b"]

        rep = lint.lint_step(jax.jit(infer), params, jnp.zeros((8, 64)))
        assert rep.by_rule("donation-miss") == []


class TestImplicitResharding:
    def test_fires_on_unscoped_collective(self, mesh8):
        def step(x):
            return jax.lax.psum(x, "data")

        m = jax.jit(jax.shard_map(step, mesh=mesh8,
                                  in_specs=(P("data"),),
                                  out_specs=P("data"), check_vma=False))
        text = m.lower(jnp.ones((8, 128))).compile().as_text()
        hits = [f for f in lint.lint_hlo_text(text)
                if f.rule == "implicit-resharding"]
        assert len(hits) == 1
        assert hits[0].severity == "warning"
        assert hits[0].op == "all-reduce"
        assert (hits[0].bytes or 0) > 0      # wire-byte cost attached

    def test_known_scope_not_flagged(self, mesh8):
        from apex_tpu.trace.spans import span

        def step(x):
            with span("ddp/sync_gradients", kind="collective"):
                return jax.lax.psum(x, "data")

        m = jax.jit(jax.shard_map(step, mesh=mesh8,
                                  in_specs=(P("data"),),
                                  out_specs=P("data"), check_vma=False))
        text = m.lower(jnp.ones((8, 128))).compile().as_text()
        assert [f for f in lint.lint_hlo_text(text)
                if f.rule == "implicit-resharding"] == []

    def test_zero_scatter_gather_scopes_known(self, mesh8):
        # the ZeRO optimizer's own collectives run under
        # zero/grad_scatter / zero/param_gather spans — planned, clean
        from apex_tpu.optim.distributed import (_all_gather_shard,
                                                _reduce_scatter_mean)

        def step(x):
            s = _reduce_scatter_mean(x, "data", 8)
            return _all_gather_shard(s, "data")

        m = jax.jit(jax.shard_map(step, mesh=mesh8, in_specs=(P(),),
                                  out_specs=P(), check_vma=False))
        text = m.lower(jnp.ones((64, 128))).compile().as_text()
        assert [f for f in lint.lint_hlo_text(text)
                if f.rule == "implicit-resharding"] == []


class TestHostTransfer:
    def test_fires_on_compiled_callback(self):
        def f(x):
            jax.debug.print("x={x}", x=x.sum())
            return x * 2

        rep = lint.lint_step(f, jnp.ones(4))
        hits = rep.by_rule("host-transfer")
        assert hits and hits[0].severity == "error"

    def test_clean_step_has_no_host_traffic(self):
        rep = lint.lint_step(lambda x: x * 2, jnp.ones(4))
        assert rep.by_rule("host-transfer") == []


class TestTilePadding:
    def test_fires_on_off_grid_dot(self):
        def mm(a, b):
            return a @ b

        text = prof.hlo.compiled_hlo(mm, jnp.zeros((9, 100)),
                                     jnp.zeros((100, 130)))
        hits = [f for f in lint.lint_hlo_text(text)
                if f.rule == "tile-padding"]
        assert hits
        assert all((f.bytes or 0) > 0 for f in hits)
        assert all(f.severity in ("info", "warning") for f in hits)

    def test_aligned_dot_does_not_fire(self):
        def mm(a, b):
            return a @ b

        text = prof.hlo.compiled_hlo(mm, jnp.zeros((8, 128)),
                                     jnp.zeros((128, 128)))
        assert [f for f in lint.lint_hlo_text(text)
                if f.rule == "tile-padding"] == []


# --- donation rule vs memory_report: the 5% agreement claim ------------------

def _bert_style_step(layers=2, hidden=64, heads=2, vocab=1000,
                     batch=2, seq=32):
    """The BERT-LAMB step at test scale — the SAME construction the
    bench row / apexlint flagship / prof_bert.py share
    (bench._bert_step_builder), with a tiny encoder."""
    import bench
    enc = models.BertEncoder(vocab, hidden=hidden, layers=layers,
                             heads=heads, max_len=seq * 2)
    step, state, (toks, labels), policy, _enc, _vars = \
        bench._bert_step_builder(batch, seq, encoder=enc, vocab=vocab)
    return step, state, toks, labels, policy


class TestDonationVsMemoryReport:
    def test_prefix_wasted_bytes_agree_within_5pct(self):
        """The PRE-fix (undonated) prof_bert-structure step: the
        donation rule's wasted-bytes total must agree with the
        memory_report params+optimizer_state attribution within 5% —
        both read the same carried-state buffers off the same compiled
        module."""
        step, state, toks, labels, pol = _bert_style_step()
        compiled = jax.jit(step).lower(state, toks, labels).compile()
        rep = lint.lint_step(step, state, toks, labels, policy=pol,
                             compiled=compiled, min_donation_bytes=0)
        wasted = rep.wasted_bytes("donation-miss")
        assert wasted > 0
        mrep = prof.memory_report(compiled)
        attr = (mrep.classes["params"]
                + mrep.classes["optimizer_state"])
        assert attr > 0
        assert abs(wasted - attr) / attr < 0.05, (wasted, attr)

    @pytest.mark.slow       # second full BERT-structure compile (~15s);
    def test_postfix_step_lints_clean(self):     # smoke lints full-size
        step, state, toks, labels, pol = _bert_style_step()
        rep = lint.lint_step(jax.jit(step, donate_argnums=(0,)),
                             state, toks, labels, policy=pol)
        assert rep.errors == [], rep.table()


# --- no-false-positive guard: flagship-structure steps -----------------------

class TestFlagshipClean:
    @pytest.mark.slow       # ResNet-50 compile ~35s on XLA:CPU; the
    # full-size flagship guard is the run_tier1.sh --smoke apexlint
    # gate (zero error-severity findings, --fail-on error)
    def test_resnet_o2_structure_lints_clean(self):
        """The bench flagship step structure (ResNet + amp O2 +
        FusedSGD + donated carried state) at test scale: zero
        error-severity findings — the guard behind the smoke gate's
        full-size run."""
        import bench
        step, (state, batch_stats), (x, y) = bench._resnet_step_builder(
            4, 32, "O2")
        rep = lint.lint_step(jax.jit(step, donate_argnums=(0, 1)),
                             state, batch_stats, x, y,
                             policy=amp.Policy.from_opt_level("O2"))
        assert rep.errors == [], rep.table()


# --- Report / baseline / JSONL plumbing --------------------------------------

class TestReportPlumbing:
    def _report(self):
        def f(x):
            jax.debug.print("x={x}", x=x.sum())
            return x * 2

        return lint.lint_step(f, jnp.ones(4), fn_name="seeded")

    def test_severity_ordering_and_table(self):
        rep = self._report()
        sevs = [f.severity for f in rep.findings]
        assert sevs == sorted(sevs, key=F.SEVERITIES.index)
        t = rep.table()
        assert "APX004" in t and "fix:" in t

    def test_rule_catalog_is_stable(self):
        assert {r.id for r in F.RULES.values()} == {
            "APX001", "APX002", "APX003", "APX004",
            "APX101", "APX102", "APX103", "APX104",
            "APX201", "APX202", "APX203", "APX204"}
        for r in F.RULES.values():
            assert r.severity in F.SEVERITIES and r.fix and r.title

    def test_baseline_round_trip(self, tmp_path):
        rep = self._report()
        assert rep.errors
        path = tmp_path / "baseline.json"
        n = lint.save_baseline(str(path), rep)
        assert n >= 1
        baseline = lint.load_baseline(str(path))
        clean = rep.apply_baseline(baseline)
        assert len(clean) == 0 and clean.suppressed == len(rep)
        # a missing baseline file is an empty baseline (the committed
        # CI file starts empty on purpose)
        assert lint.load_baseline(str(tmp_path / "missing.json")) == []

    def test_committed_baseline_starts_empty(self):
        path = os.path.join(_REPO_ROOT, "scripts",
                            "apexlint_baseline.json")
        assert lint.load_baseline(path) == []

    def test_jsonl_round_trip_validates(self, tmp_path):
        """Report -> MetricsLogger lint channel -> JSONL ->
        check_metrics_schema --kind lint (module-level and subprocess
        CLI) — the round-trip acceptance test."""
        sys.path.insert(0, os.path.join(_REPO_ROOT, "scripts"))
        try:
            import check_metrics_schema as cms
        finally:
            sys.path.pop(0)
        rep = self._report()
        path = tmp_path / "lint.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], lint_sink=monitor.JSONLSink(str(path)))
        logger.attach_lint_report(rep)
        logger.close()
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 1 + len(rep)
        assert json.loads(lines[0])["kind"] == "lint_report"
        assert cms.check_lint_lines(lines) == []
        proc = subprocess.run(
            [sys.executable, _SCHEMA_SCRIPT, "--kind", "lint",
             str(path)], capture_output=True, text=True, cwd=_REPO_ROOT)
        assert proc.returncode == 0, proc.stderr
        # and the validator actually rejects garbage
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"kind": "lint_finding", "rule": "x"}\n')
        assert cms.check_lint_lines(
            bad.read_text().splitlines()) != []

    def test_fingerprint_excludes_bytes(self):
        a = F.Finding(rule="donation-miss", message="m", op="arg0",
                      scope="state.params", bytes=100)
        b = F.Finding(rule="donation-miss", message="m", op="arg0",
                      scope="state.params", bytes=999)
        assert a.fingerprint() == b.fingerprint()


# --- compile-check cases ------------------------------------------------------

class TestCompileCheckCases:
    def _case(self, name):
        from apex_tpu.ops import compile_check as cc
        return dict(cc.CASES)[name]

    def test_no_extra_dispatch_case(self):
        self._case("lint/no-extra-dispatch")()

    @pytest.mark.slow       # compiles 5 kernel families (~20s); also
    def test_kernel_sweep_case(self):            # runs on-device via
        self._case("lint/kernel-sweep")()        # python -m apex_tpu.ops
