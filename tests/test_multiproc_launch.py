"""Two-process launch bring-up — the reference's 2-GPU distributed tier.

Spawns two ACTUAL processes that rendezvous through
``parallel.launch.distributed_init``'s MASTER_ADDR/RANK/WORLD_SIZE env
conventions (`apex/parallel/multiproc.py:1-35`), form a jax.distributed
CPU cluster, and run one psum'd DDP gradient step across the global
device set (`tests/distributed/DDP/ddp_race_condition_test.py:28-70`).
Every other distributed test in this suite runs single-process on the
virtual mesh; this one proves the multi-process rendezvous path
end-to-end (VERDICT r3 item 5).
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_CHILD = textwrap.dedent("""
    import os, sys
    import jax
    from apex_tpu import _compat
    jax.config.update("jax_platforms", "cpu")
    _compat.request_cpu_devices(2)

    from apex_tpu.parallel.launch import distributed_init

    # resolve rendezvous purely from the reference's env conventions
    distributed_init()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()
    assert rank == int(os.environ["RANK"]), (rank, os.environ["RANK"])
    assert len(jax.devices()) == 4, jax.devices()   # 2 procs x 2 cpu

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from apex_tpu import parallel
    from apex_tpu.parallel import DistributedDataParallel

    mesh = parallel.data_parallel_mesh()
    ddp = DistributedDataParallel(mesh)

    def step(w, x, y):
        def loss_fn(w):
            pred = x @ w
            return jnp.mean((pred - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(w)
        g = ddp.sync(g)                       # psum / world
        return w - 0.1 * g, jax.lax.pmean(loss, ddp.axis_name)

    spmd = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(parallel.DATA_AXIS), P(parallel.DATA_AXIS)),
        out_specs=(P(), P()), check_vma=False))

    # identical params everywhere; rank-dependent data shards arrive
    # via the addressable slice of a global array
    np_rng = np.random.RandomState(0)
    w = jnp.asarray(np_rng.randn(8, 1), jnp.float32)
    xg = np_rng.randn(16, 8).astype("float32")
    yg = np_rng.randn(16, 1).astype("float32")
    xs = jax.device_put(xg, parallel.batch_sharding(mesh))
    ys = jax.device_put(yg, parallel.batch_sharding(mesh))
    w2, loss = spmd(w, xs, ys)

    # the synced step must equal the single-process full-batch step
    def full(w):
        return jnp.mean((xg @ w - yg) ** 2)
    wref = w - 0.1 * jax.grad(full)(w)
    np.testing.assert_allclose(np.asarray(w2), np.asarray(wref),
                               rtol=1e-5, atol=1e-6)
    print(f"OK rank={rank} loss={float(loss):.6f}", flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_ddp_step(tmp_path):
    port = _free_port()
    env_base = {
        **os.environ,
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(port),
        "WORLD_SIZE": "2",
        "JAX_PLATFORMS": "cpu",
        # the child config sets device count; keep XLA quiet
        "TF_CPP_MIN_LOG_LEVEL": "2",
    }
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(2):
        env = {**env_base, "RANK": str(rank)}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("two-process rendezvous timed out:\n"
                    + "\n---\n".join(o or "" for o in outs))

    codes = [p.returncode for p in procs]
    joined = "\n---rank-output---\n".join(outs)
    if any(c != 0 for c in codes):
        # environment-level inability to form a cluster (no loopback
        # networking, distributed service unsupported) → skip, not fail;
        # an assertion inside the child is a real failure
        if ("AssertionError" not in joined
                and "Mismatch" not in joined
                and any(s in joined for s in
                        ("UNAVAILABLE", "DEADLINE_EXCEEDED",
                         "Permission denied", "unreachable",
                         "aren't implemented on the CPU backend"))):
            pytest.skip(f"cluster bring-up unsupported here:\n{joined}")
        pytest.fail(f"child exit codes {codes}:\n{joined}")
    assert all("OK rank=" in o for o in outs), joined
