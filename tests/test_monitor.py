"""apex_tpu.monitor — in-graph telemetry + host metrics pipeline.

Covers the ISSUE-1 acceptance contract: loss-scale event counters
(growth / backoff / overflow / skip) advance correctly under the
schedule, the Metrics pytree survives jit and checkpointing as a pure
pytree, a monitored 5-step jitted toy train loop emits a JSONL stream
that `scripts/check_metrics_schema.py` validates, and monitoring adds no
HLO modules / host traffic to the compiled step (the zero-extra-dispatch
property).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, monitor
from apex_tpu.fp16_utils import FP16_Optimizer
from apex_tpu.monitor.metrics import Metrics, metrics_init
from apex_tpu.optim import FusedSGD

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_SCHEMA_SCRIPT = os.path.join(_REPO_ROOT, "scripts",
                              "check_metrics_schema.py")


# --- the in-graph Metrics pytree ---------------------------------------------

def test_metrics_is_pure_pytree():
    m = metrics_init()
    leaves, treedef = jax.tree_util.tree_flatten(m)
    assert len(leaves) == len(Metrics._fields)
    assert all(isinstance(l, jax.Array) for l in leaves)
    # checkpoint round-trip: host numpy and back, structure preserved
    host = jax.tree_util.tree_map(np.asarray, m)
    back = jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(l) for l in jax.tree_util.tree_leaves(host)])
    assert int(back.step) == 0 and float(back.loss_scale) == 1.0


def test_metrics_roundtrips_through_jit():
    @jax.jit
    def advance(m):
        return m.count_step(jnp.bool_(False)).record_loss(3.5)

    m = advance(metrics_init())
    assert isinstance(m, Metrics)
    assert int(m.step) == 1
    assert int(m.skip_count) == 1
    assert float(m.loss) == 3.5


# --- loss-scale event telemetry ----------------------------------------------

def test_scaler_growth_events_after_interval():
    cfg = amp.LossScaleConfig(init_scale=4.0, growth_interval=3)
    st = amp.loss_scale_init(cfg)
    m = metrics_init()
    for i in range(6):
        st, m = amp.loss_scale_update(st, jnp.bool_(True), cfg, metrics=m)
    # two full growth intervals of 3 finite steps each
    assert float(st.loss_scale) == 16.0
    assert int(m.growth_count) == 2
    assert int(m.backoff_count) == 0
    assert int(m.overflow_count) == 0
    assert float(m.loss_scale) == 16.0


def test_scaler_backoff_events_on_overflow():
    cfg = amp.LossScaleConfig(init_scale=2.0 ** 16)
    st = amp.loss_scale_init(cfg)
    m = metrics_init()
    st, m = amp.loss_scale_update(st, jnp.bool_(False), cfg, metrics=m)
    assert float(st.loss_scale) == 2.0 ** 15
    assert int(m.overflow_count) == 1
    assert int(m.backoff_count) == 1
    assert int(m.growth_count) == 0
    st, m = amp.loss_scale_update(st, jnp.bool_(True), cfg, metrics=m)
    assert int(m.overflow_count) == 1  # finite step adds nothing


def test_scaler_static_scale_still_counts_overflows():
    cfg = amp.LossScaleConfig(init_scale=128.0, dynamic=False)
    st = amp.loss_scale_init(cfg)
    m = metrics_init()
    st, m = amp.loss_scale_update(st, jnp.bool_(False), cfg, metrics=m)
    assert float(st.loss_scale) == 128.0      # static: no backoff
    assert int(m.overflow_count) == 1
    assert int(m.backoff_count) == 0
    assert float(m.loss_scale) == 128.0


def _toy_amp(monitor_flag, half_dtype=jnp.float16, init_scale=None):
    params = {"w": jnp.full((4, 2), 0.5, jnp.float32),
              "b": jnp.zeros((2,), jnp.float32)}
    overrides = {}
    if init_scale is not None:
        overrides["loss_scale"] = init_scale
    amp_opt, state = amp.initialize(
        params, FusedSGD(lr=0.1), "O2", half_dtype=half_dtype,
        verbosity=0, monitor=monitor_flag, **overrides)
    return amp_opt, state


def test_amp_skip_counts_on_overflow_step():
    amp_opt, state = _toy_amp(True)
    x = jnp.ones((4, 4), jnp.float32)

    @jax.jit
    def step(state, scale):
        def loss_fn(p):
            return jnp.mean(x @ p["w"] + p["b"]) * scale
        state, _, finite = amp_opt.step(state, loss_fn)
        return state, finite

    state, finite = step(state, jnp.float32(1.0))
    assert bool(finite)
    m = state.metrics
    assert int(m.step) == 1 and int(m.skip_count) == 0
    assert float(m.grad_norm) > 0.0
    gnorm_before = float(m.grad_norm)

    state, finite = step(state, jnp.float32(jnp.inf))  # force overflow
    assert not bool(finite)
    m = state.metrics
    assert int(m.step) == 2
    assert int(m.skip_count) == 1
    assert int(m.overflow_count) == 1
    assert int(m.backoff_count) == 1
    # gauge holds the last finite value (no inf on the wire)
    assert float(m.grad_norm) == pytest.approx(gnorm_before)
    assert np.isfinite(float(m.param_norm))
    # committed training state did not move on the skipped step
    assert int(state.step) == 1


def test_amp_monitor_off_keeps_metrics_none():
    amp_opt, state = _toy_amp(False)
    assert state.metrics is None
    x = jnp.ones((4, 4), jnp.float32)

    @jax.jit
    def step(state):
        def loss_fn(p):
            return jnp.mean(x @ p["w"] + p["b"])
        state, loss, _ = amp_opt.step(state, loss_fn)
        return state, loss

    state, _ = step(state)
    assert state.metrics is None


def test_fp16_optimizer_monitor_hook():
    opt = FP16_Optimizer(FusedSGD(lr=0.1), dynamic_loss_scale=True,
                         monitor=True)
    params = {"w": jnp.ones((4, 2), jnp.float32)}
    state = opt.init(params)
    x = jnp.ones((3, 4), jnp.float32)

    @jax.jit
    def train(state):
        def loss_fn(mp):
            return jnp.mean(jnp.square(x @ mp["w"]))
        loss, grads, finite, state = opt.backward(state, loss_fn)
        state = opt.step(state, grads, finite)
        return state, loss

    state, _ = train(state)
    m = state.metrics
    assert int(m.step) == 1
    assert float(m.loss_scale) == float(state.scaler.loss_scale)
    assert float(m.param_norm) > 0.0
    # metrics survive the legacy state_dict round-trip
    sd = opt.state_dict(state)
    restored = opt.load_state_dict(state, sd)
    assert int(restored.metrics.step) == 1


# --- host pipeline: logger + sinks -------------------------------------------

def test_logger_amortized_flush_and_sinks(tmp_path):
    import io
    jsonl = tmp_path / "m.jsonl"
    csv_path = tmp_path / "m.csv"
    table = io.StringIO()
    logger = monitor.MetricsLogger(
        sinks=[monitor.StdoutSink(table), monitor.JSONLSink(str(jsonl)),
               monitor.CSVSink(str(csv_path))],
        flush_every=4)
    m = metrics_init()
    for i in range(6):
        m = m.count_step(jnp.bool_(True)).record_loss(float(i))
        logger.record(m)
        # nothing reaches sinks until the flush boundary
        if i < 3:
            assert jsonl.read_text() == "" if jsonl.exists() else True
    logger.close()
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert len(lines) == 6
    assert [r["step"] for r in lines] == [1, 2, 3, 4, 5, 6]
    assert lines[0]["step_time_ms"] is None       # no predecessor
    assert all(r["step_time_ms"] is not None for r in lines[1:])
    assert "step" in table.getvalue() and "gnorm" in table.getvalue()
    csv_lines = csv_path.read_text().splitlines()
    assert csv_lines[0].startswith("step,")
    assert len(csv_lines) == 7


def test_logger_donation_safe_survives_donated_steps(tmp_path):
    """A step jitted with donate_argnums over the state carrying the
    metrics invalidates the buffers a buffered record points at;
    donation_safe=True snapshots each record so the amortized flush
    still lands every row (the bench --monitor/--trace loops and the
    DDP example donate exactly like this)."""
    jsonl = tmp_path / "m.jsonl"

    @jax.jit
    def make(m):
        return m

    def step(m):
        return m.count_step(jnp.bool_(True)).record_loss(1.0)

    jstep = jax.jit(step, donate_argnums=(0,))
    logger = monitor.MetricsLogger(
        sinks=[monitor.JSONLSink(str(jsonl))], flush_every=10,
        donation_safe=True)
    m = make(metrics_init())
    for _ in range(4):
        m = jstep(m)
        logger.record(m)            # donated away by the NEXT call
    logger.close()
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert [r["step"] for r in lines] == [1, 2, 3, 4]

    # without the flag, the robust flush salvages what survives (the
    # last record) instead of raising and losing the whole window
    jsonl2 = tmp_path / "m2.jsonl"
    logger2 = monitor.MetricsLogger(
        sinks=[monitor.JSONLSink(str(jsonl2))], flush_every=10)
    m = make(metrics_init())
    for _ in range(4):
        m = jstep(m)
        logger2.record(m)
    logger2.close()
    lines2 = [json.loads(l) for l in jsonl2.read_text().splitlines()]
    assert len(lines2) >= 1
    assert lines2[-1]["step"] == 4


def test_metrics_snapshot_copies_buffers():
    m = metrics_init().count_step(jnp.bool_(True))
    snap = monitor.metrics_snapshot(m)
    assert float(snap.step) == float(m.step)
    for a, b in zip(jax.tree_util.tree_leaves(m),
                    jax.tree_util.tree_leaves(snap)):
        assert a.unsafe_buffer_pointer() != b.unsafe_buffer_pointer()


def test_logger_nulls_nonfinite_gauges(tmp_path):
    jsonl = tmp_path / "m.jsonl"
    logger = monitor.MetricsLogger(sinks=[monitor.JSONLSink(str(jsonl))],
                                   flush_every=1)
    m = metrics_init().record_loss(jnp.float32(jnp.nan)).count_step(True)
    logger.record(m)
    logger.close()
    rec = json.loads(jsonl.read_text().splitlines()[0])
    assert rec["loss"] is None


# --- collective-bytes accounting ---------------------------------------------

def test_collective_bytes_from_synthetic_hlo():
    text = """
HloModule m
ENTRY main {
  %p0 = f32[1024]{0} parameter(0)
  %ar = f32[1024]{0} all-reduce(f32[1024]{0} %p0), to_apply=%add
  %ag = f32[8192]{0} all-gather(f32[1024]{0} %ar), dimensions={0}
  %start = (f32[1024]{0}, f32[1024]{0}) all-reduce-start(f32[1024]{0} %p0), to_apply=%add
  %done = f32[1024]{0} all-reduce-done((f32[1024]{0}, f32[1024]{0}) %start)
  ROOT %t = (f32[1024]{0}) tuple(f32[1024]{0} %done)
}
"""
    got = monitor.collective_bytes_from_text(text)
    # sync all-reduce (4KiB) + async pair counted once at -done (4KiB)
    assert got["all-reduce"] == 2 * 1024 * 4
    assert got["all-gather"] == 8192 * 4
    assert got["total"] == 2 * 1024 * 4 + 8192 * 4


def test_collective_bytes_of_psum_step(mesh8):
    from jax.sharding import PartitionSpec as P

    def step(x):
        return jax.lax.psum(x, "data")

    mapped = jax.jit(jax.shard_map(step, mesh=mesh8, in_specs=(P("data"),),
                                   out_specs=P(), check_vma=False))
    x = jnp.ones((8, 128), jnp.float32)
    got = monitor.collective_bytes(mapped, x)
    assert got["total"] >= 128 * 4    # at least the per-shard result


# --- the acceptance loop: JSONL stream + schema + zero extra dispatch --------

def _train_loop_5steps(jsonl_path):
    amp_opt, state = _toy_amp(True)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 4).astype(np.float32))
    y = jnp.asarray(rng.randn(8, 2).astype(np.float32))

    @jax.jit
    def train_step(state, x, y):
        def loss_fn(p):
            return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
        state, loss, _ = amp_opt.step(state, loss_fn)
        return state, loss

    logger = monitor.MetricsLogger(
        sinks=[monitor.JSONLSink(str(jsonl_path))], flush_every=2)
    logger.attach(train_step, state, x, y)
    for _ in range(5):
        state, _ = train_step(state, x, y)
        logger.record(state.metrics)
    logger.close()
    return state


def test_five_step_loop_emits_valid_schema(tmp_path):
    jsonl = tmp_path / "metrics.jsonl"
    _train_loop_5steps(jsonl)
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert len(lines) == 5
    for key in ("loss_scale", "skip_count", "grad_norm", "step_time_ms",
                "mfu"):
        assert all(key in r for r in lines)
    assert [r["step"] for r in lines] == [1, 2, 3, 4, 5]
    # the wire format passes the CI validator (subprocess — the exact
    # tool a deployment would run)
    r = subprocess.run([sys.executable, _SCHEMA_SCRIPT, str(jsonl)],
                       capture_output=True, text=True, cwd=_REPO_ROOT)
    assert r.returncode == 0, r.stderr


def test_schema_script_rejects_bad_streams(tmp_path):
    from importlib import util as _util
    spec = _util.spec_from_file_location("check_metrics_schema",
                                        _SCHEMA_SCRIPT)
    mod = _util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    ok_rec = {k: 0 for k in mod.REQUIRED}
    ok_rec.update(step=1, loss=0.5, loss_scale=1.0, grad_norm=0.1,
                  param_norm=1.0, step_time_ms=2.0,
                  throughput_steps_per_s=10.0, mfu=None)
    assert mod.check_lines([json.dumps(ok_rec)]) == []
    # missing key
    bad = dict(ok_rec); bad.pop("loss_scale")
    assert mod.check_lines([json.dumps(bad)])
    # non-monotonic step
    second = dict(ok_rec)
    assert mod.check_lines([json.dumps(ok_rec), json.dumps(second)])
    # non-finite value
    bad = dict(ok_rec); bad["grad_norm"] = float("inf")
    assert mod.check_lines([json.dumps(bad, allow_nan=True)])
    # empty file
    assert mod.check_lines([])


def test_monitoring_adds_no_modules_or_host_ops():
    """The acceptance compile-check: monitored vs unmonitored toy loop —
    same HLO module count, no host traffic in the monitored program
    (also registered as `monitor/no-extra-dispatch` in
    `python -m apex_tpu.ops` for on-device validation)."""
    x = jnp.ones((8, 4), jnp.float32)
    y = jnp.ones((8, 2), jnp.float32)

    def build(flag):
        amp_opt, state = _toy_amp(flag)

        def train_step(state, x, y):
            def loss_fn(p):
                return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))
            state, loss, _ = amp_opt.step(state, loss_fn)
            return state, loss
        return jax.jit(train_step), state

    mon, mon_state = build(True)
    plain, plain_state = build(False)
    n_mon, host = monitor.module_count_and_host_ops(mon, mon_state, x, y)
    n_plain, _ = monitor.module_count_and_host_ops(plain, plain_state, x, y)
    assert n_mon == n_plain == 1
    assert host == []
