"""Ring / Ulysses sequence parallelism vs full attention on one device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.ops import attention as A


def rand_qkv(rng, b, s, h, d):
    return (jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
            for _ in range(3))


def _run(mesh, fn, *args):
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=P(None, "data"), out_specs=P(None, "data"),
        check_vma=False))(*args)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh8, causal):
        rng = np.random.RandomState(0)
        q, k, v = rand_qkv(rng, 2, 8 * 32, 2, 32)

        def ring(q, k, v):
            return parallel.ring_attention(q, k, v, "data", causal=causal)

        got = _run(mesh8, ring, q, k, v)
        ref = A.attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=5e-5)

    def test_gradients_match(self, mesh8):
        rng = np.random.RandomState(1)
        q, k, v = rand_qkv(rng, 1, 8 * 16, 2, 32)

        def ring_loss(q, k, v):
            # local sum only: the global loss is the implicit sum of the
            # per-device losses, so each shard's grad is already global —
            # a psum here would double-count via its transpose
            o = parallel.ring_attention(q, k, v, "data", causal=True)
            return jnp.sum(jnp.sin(o))

        def g(q, k, v):
            return jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)

        got = jax.jit(jax.shard_map(
            g, mesh=mesh8, in_specs=P(None, "data"),
            out_specs=P(None, "data"), check_vma=False))(q, k, v)

        ref = jax.grad(
            lambda q_, k_, v_: jnp.sum(jnp.sin(
                A.attention_reference(q_, k_, v_, causal=True))),
            argnums=(0, 1, 2))(q, k, v)
        for a, e, name in zip(got, ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       atol=1e-4, err_msg=f"d{name}")


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh8, causal):
        rng = np.random.RandomState(2)
        q, k, v = rand_qkv(rng, 2, 8 * 32, 8, 16)  # 8 heads / 8 devices

        def uly(q, k, v):
            return parallel.ulysses_attention(q, k, v, "data",
                                              causal=causal)

        got = _run(mesh8, uly, q, k, v)
        ref = A.attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=5e-5)

    def test_gradients(self, mesh8):
        rng = np.random.RandomState(3)
        q, k, v = rand_qkv(rng, 1, 8 * 16, 8, 16)

        def loss(q, k, v):
            o = parallel.ulysses_attention(q, k, v, "data")
            return jnp.sum(o * o)

        def g(q, k, v):
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        got = jax.jit(jax.shard_map(
            g, mesh=mesh8, in_specs=P(None, "data"),
            out_specs=P(None, "data"), check_vma=False))(q, k, v)
        ref = jax.grad(
            lambda q_, k_, v_: jnp.sum(
                A.attention_reference(q_, k_, v_) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, e in zip(got, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       atol=1e-4)


class TestRingDropout:
    """VERDICT r4 item 4: SP with training-grade semantics — the ring
    dropout mask equals the single-device fast path's mask bitwise
    (same counter hash at global block coordinates), so outputs and
    grads agree to fp tolerance. A flipped keep bit would move an
    output element by O(p·v) ≫ the tolerances here."""

    def _mesh2(self):
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices()[:2]), ("data",))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_local_fast_path(self, causal):
        mesh = self._mesh2()
        rng = np.random.RandomState(3)
        q, k, v = rand_qkv(rng, 1, 2 * 512, 2, 64)
        seed = 1234

        def ring(q, k, v):
            return parallel.ring_attention(
                q, k, v, "data", causal=causal, dropout_rate=0.3,
                dropout_seed=seed)

        got = _run(mesh, ring, q, k, v)
        ref = A.flash_attention(q, k, v, causal=causal,
                                dropout_rate=0.3, dropout_seed=seed)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    def test_gradients_match_local_fast_path(self):
        mesh = self._mesh2()
        rng = np.random.RandomState(4)
        q, k, v = rand_qkv(rng, 1, 2 * 512, 2, 64)
        seed = 77

        def ring_loss(q, k, v):
            o = parallel.ring_attention(q, k, v, "data", causal=True,
                                        dropout_rate=0.25,
                                        dropout_seed=seed)
            return jnp.sum(jnp.sin(o))

        got = jax.jit(jax.shard_map(
            lambda q, k, v: jax.grad(ring_loss, argnums=(0, 1, 2))(
                q, k, v),
            mesh=mesh, in_specs=P(None, "data"),
            out_specs=P(None, "data"), check_vma=False))(q, k, v)

        ref = jax.grad(
            lambda q_, k_, v_: jnp.sum(jnp.sin(A.flash_attention(
                q_, k_, v_, causal=True, dropout_rate=0.25,
                dropout_seed=seed))),
            argnums=(0, 1, 2))(q, k, v)
        for a, e, name in zip(got, ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       atol=2e-4, err_msg=f"d{name}")

    def test_multiblock_shards_match_local(self):
        """S_local=1024 → two 512-blocks per shard: the per-hop offsets
        are in BLOCK units (my*nqb, src*nkb with nqb=nkb=2), so this
        geometry catches offset-unit bugs the single-block case
        cannot."""
        mesh = self._mesh2()
        rng = np.random.RandomState(8)
        q, k, v = rand_qkv(rng, 1, 2 * 1024, 2, 64)
        seed = 55

        def ring(q, k, v):
            return parallel.ring_attention(
                q, k, v, "data", causal=True, dropout_rate=0.3,
                dropout_seed=seed)

        got = _run(mesh, ring, q, k, v)
        ref = A.flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                                dropout_seed=seed)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    def test_unaligned_shard_raises(self):
        mesh = self._mesh2()
        rng = np.random.RandomState(5)
        q, k, v = rand_qkv(rng, 1, 2 * 128, 2, 64)

        def ring(q, k, v):
            return parallel.ring_attention(q, k, v, "data",
                                           dropout_rate=0.1,
                                           dropout_seed=0)

        with pytest.raises(ValueError, match="512 dropout tile"):
            _run(mesh, ring, q, k, v)

    def test_ulysses_dropout_raises(self):
        """The load-bearing refusal (docs/parallel.md#ulysses-dropout):
        after the head re-shard the kernels' batch·head mask coordinate
        cannot reproduce the single-device mask, so the call must fail
        LOUDLY, name the working alternative with its arguments, and
        point at the docs — not silently train with a divergent mask."""
        mesh = self._mesh2()
        rng = np.random.RandomState(6)
        q, k, v = rand_qkv(rng, 1, 2 * 128, 2, 64)
        with pytest.raises(NotImplementedError) as ei:
            _run(mesh, lambda q, k, v: parallel.ulysses_attention(
                q, k, v, "data", dropout_rate=0.1, dropout_seed=0),
                 q, k, v)
        msg = str(ei.value)
        # actionable: the exact alternative call, with the axis and
        # rate the user passed, plus the docs anchor and the why
        assert "ring_attention(q, k, v, 'data', dropout_rate=0.1" in msg
        assert "docs/parallel.md#ulysses-dropout" in msg
        assert "batch-head mask coordinate" in msg

    def test_ulysses_dropout_zero_rate_still_works(self):
        """The refusal is scoped to dropout_rate > 0 — rate 0 (eval, or
        train without dropout) must run, not raise."""
        mesh = self._mesh2()
        rng = np.random.RandomState(7)
        q, k, v = rand_qkv(rng, 1, 2 * 128, 2, 64)
        out = _run(mesh, lambda q, k, v: parallel.ulysses_attention(
            q, k, v, "data", dropout_rate=0.0, dropout_seed=None),
            q, k, v)
        assert np.isfinite(np.asarray(out)).all()
