"""ResNet (v1.5) — the imagenet-example model family.

The reference's canonical benchmark drives torchvision ResNet-50 through
amp + apex DDP (`examples/imagenet/main_amp.py:130-180`). This is the
TPU-native equivalent: NHWC layout (TPU conv-native), flax modules, BN that
can sync over a mesh axis (``bn_axis_name`` ↔ ``--sync_bn``,
`main_amp.py:142-145`), and bottleneck blocks with the stride-on-3x3
placement (v1.5) that torchvision uses.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm
from apex_tpu.ops.bn_act import FusedBNAct


class _BN(nn.Module):
    """BatchNorm unit, optionally with fused residual-add and ReLU.

    ``dtype`` is the *activation* dtype (output in that dtype, stats and
    scale/offset always fp32) — keep_batchnorm_fp32 the TPU way: fp32
    parameters and statistics, half activations in and out, the cast
    fused into the normalize instead of materialized in HBM.

    ``fused=True`` (default) routes through :class:`FusedBNAct`, whose
    hand-written VJP saves only the conv output + per-channel stats and
    recomputes x̂/the ReLU mask — the traffic-minimal backward (the role
    of the reference's `nhwc_batch_norm_kernel.h` fused kernels). The
    unfused path keeps the round-2 module structure (flax BatchNorm /
    SyncBatchNorm submodule) as the autodiff oracle; note the param
    trees differ between the two (documented in docs/models.md).
    """
    features: int
    axis_name: Optional[str] = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    init_scale: float = 1.0
    dtype: Optional[Any] = None
    relu: bool = False
    fused: bool = True

    @nn.compact
    def __call__(self, x, residual=None, train: bool = True):
        if self.fused:
            z = FusedBNAct(
                num_features=self.features, relu=self.relu,
                momentum=self.momentum, epsilon=self.epsilon,
                axis_name=self.axis_name, init_scale=self.init_scale,
                dtype=self.dtype)(x, residual, train=train)
            return z
        if self.dtype is not None:
            x = x.astype(self.dtype)
            if residual is not None:
                residual = residual.astype(self.dtype)
        if self.axis_name is not None:
            bn = SyncBatchNorm(
                num_features=self.features, momentum=1 - self.momentum,
                epsilon=self.epsilon, axis_name=self.axis_name,
                scale_init=nn.initializers.constant(self.init_scale))
            y = bn(x, use_running_average=not train)
        else:
            bn = nn.BatchNorm(
                use_running_average=not train, momentum=self.momentum,
                epsilon=self.epsilon, dtype=self.dtype,
                scale_init=nn.initializers.constant(self.init_scale))
            y = bn(x)
        if residual is not None:
            y = y + residual
        if self.relu:
            y = nn.relu(y)
        return y


class _StemConv(nn.Module):
    """The 7x7/2 stem conv, optionally via 2x2 space-to-depth.

    A C=3 input maps pathologically onto the MXU: 3 of 128 lanes carry
    data in the contracting dimension, so the stem's forward and weight
    gradient run far below roofline. The space-to-depth transform packs
    each 2x2 pixel cell into channels — (B, H, W, 3) → (B, H/2, W/2, 12)
    — and runs the arithmetically identical (4, 4, 12, K) stride-1 conv
    (the kernel zero-padded 7→8 taps so the stride-2 window aligns with
    whole cells). Parameters keep the canonical (7, 7, 3, K) shape, so
    checkpoints interchange with the plain stem; the kernel re-layout is
    37K params of in-graph reshuffling and gradients flow through it.
    """
    features: int
    space_to_depth: bool = True
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        k = self.param("kernel", nn.initializers.lecun_normal(),
                       (7, 7, x.shape[-1], self.features), jnp.float32)
        # same dtype semantics as nn.Conv: explicit dtype wins, otherwise
        # promote input/param dtypes to a common compute dtype
        x, k = nn.dtypes.promote_dtype(x, k, dtype=self.dtype)
        b, h, w, c = x.shape
        if not self.space_to_depth or h % 2 or w % 2 or c != 3:
            return jax.lax.conv_general_dilated(
                x, k, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # input: pack 2x2 cells into channels, sub-order (r, s, c)
        xs = x.reshape(b, h // 2, 2, w // 2, 2, c)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2,
                                                    4 * c)
        # kernel: zero-pad the window to 8x8 at the leading edge (the
        # stride-2 window [2i-3, 2i+3] becomes the cell-aligned
        # [2i-4, 2i+3]), then split taps p=2ρ+r into (cell ρ, sub r)
        k8 = jnp.pad(k, ((1, 0), (1, 0), (0, 0), (0, 0)))
        ks = k8.reshape(4, 2, 4, 2, c, self.features)
        ks = ks.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c,
                                                    self.features)
        # cells [i-2, i+1] feed output i → padding (2, 1), stride 1
        return jax.lax.conv_general_dilated(
            xs, ks, window_strides=(1, 1), padding=[(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


# the stem is MXU-bound like any conv: under auto_cast(O1) it must cast
# to the half dtype with the rest of the whitelist (nn.Conv matches by
# isinstance; a custom module needs registering)
from apex_tpu.amp.lists import register_half_module as _reg_half
_reg_half(_StemConv)
del _reg_half


class BottleneckBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    bn_axis_name: Optional[str] = None
    dtype: Optional[Any] = None
    fused_bn: bool = True
    #: distributed-dgrad conv+BN backward (ops/conv_bn.py experiment):
    #: None = off, "join" = residual-join unit only, "all" = every unit
    dx_distribute: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(_BN, axis_name=self.bn_axis_name, dtype=self.dtype,
                     fused=self.fused_bn)
        from apex_tpu.ops.conv_bn import ConvBNAct
        cba = partial(ConvBNAct, axis_name=self.bn_axis_name,
                      dtype=self.dtype)
        dist_all = self.dx_distribute == "all"
        dist_join = self.dx_distribute in ("all", "join")
        residual = x
        if dist_all:
            y = cba(self.features, (1, 1), relu=True)(x, train=train)
            y = cba(self.features, (3, 3), self.strides,
                    relu=True)(y, train=train)
        else:
            y = conv(self.features, (1, 1))(x)
            y = bn(self.features, relu=True)(y, train=train)
            y = conv(self.features, (3, 3), self.strides)(y)
            y = bn(self.features, relu=True)(y, train=train)
        need_proj = residual.shape[-1] != self.features * 4 \
            or self.strides != (1, 1)
        # module creation order on the default path is load-bearing:
        # flax auto-names (Conv_2 = final 1x1, Conv_3 = projection) are
        # the checkpoint layout — only the experimental dist paths may
        # reorder (their parameter trees are new anyway)
        if not dist_join:
            y = conv(self.features * 4, (1, 1))(y)
        if need_proj:
            if dist_all:
                residual = cba(self.features * 4, (1, 1), self.strides,
                               relu=False)(x, train=train)
            else:
                residual = conv(self.features * 4, (1, 1),
                                self.strides)(x)
                residual = bn(self.features * 4)(residual, train=train)
        # zero-init the last BN scale: standard ResNet recipe (identity
        # residual at init); the residual add + relu fuse into this unit
        if dist_join:
            return cba(self.features * 4, (1, 1), relu=True,
                       init_scale=0.0)(y, residual, train=train)
        return bn(self.features * 4, init_scale=0.0, relu=True)(
            y, residual, train=train)


class BasicBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    bn_axis_name: Optional[str] = None
    dtype: Optional[Any] = None
    fused_bn: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(_BN, axis_name=self.bn_axis_name, dtype=self.dtype,
                     fused=self.fused_bn)
        residual = x
        y = conv(self.features, (3, 3), self.strides)(x)
        y = bn(self.features, relu=True)(y, train=train)
        y = conv(self.features, (3, 3))(y)
        if residual.shape[-1] != self.features or self.strides != (1, 1):
            residual = conv(self.features, (1, 1), self.strides)(x)
            residual = bn(self.features)(residual, train=train)
        return bn(self.features, init_scale=0.0, relu=True)(
            y, residual, train=train)


class ResNet(nn.Module):
    """NHWC ResNet; input (N, H, W, 3)."""
    stage_sizes: Sequence[int]
    block: Any = BottleneckBlock
    num_classes: int = 1000
    width: int = 64
    bn_axis_name: Optional[str] = None
    #: activation/compute dtype — set to ``policy.compute_dtype`` for mixed
    #: precision (the O2 model-cast; params stay ``param_dtype`` fp32 and
    #: are cast per-op by flax, masters live in AmpState).
    dtype: Optional[Any] = None
    #: run the stem via 2x2 space-to-depth (MXU-friendly C=12 layout);
    #: automatically falls back to the plain 7x7/2 conv for odd sizes
    space_to_depth: bool = True
    #: minimal-residual fused BN(+add)(+relu) backward (see ops/bn_act.py);
    #: False = plain flax BatchNorm autodiff (the numeric oracle)
    fused_bn: bool = True
    #: distributed-dgrad conv+BN experiment (ops/conv_bn.py): None | "join"
    #: | "all" — changes the parameter tree of the affected units
    dx_distribute: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.dtype is not None:
            x = x.astype(self.dtype)  # patched-forward input cast
        y = _StemConv(self.width, space_to_depth=self.space_to_depth,
                      dtype=self.dtype, name="stem_conv")(x)
        y = _BN(self.width, self.bn_axis_name, dtype=self.dtype,
                relu=True, fused=self.fused_bn)(y, train=train)
        y = nn.max_pool(y, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                kw = {}
                if self.dx_distribute is not None:
                    if self.dx_distribute not in ("join", "all"):
                        raise ValueError(
                            "dx_distribute must be None, 'join' or "
                            f"'all', got {self.dx_distribute!r}")
                    if self.block is not BottleneckBlock:
                        raise ValueError(
                            "dx_distribute is only implemented for "
                            f"BottleneckBlock, got {self.block!r}")
                    kw["dx_distribute"] = self.dx_distribute
                y = self.block(self.width * 2 ** i, strides,
                               self.bn_axis_name, self.dtype,
                               self.fused_bn, **kw)(y, train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(y)


def ResNet18(**kw):
    return ResNet(stage_sizes=[2, 2, 2, 2], block=BasicBlock, **kw)


def ResNet50(**kw):
    return ResNet(stage_sizes=[3, 4, 6, 3], block=BottleneckBlock, **kw)


def ResNet101(**kw):
    return ResNet(stage_sizes=[3, 4, 23, 3], block=BottleneckBlock, **kw)


#: fwd-pass MACs per 224x224 image — used by bench MFU accounting.
RESNET50_FLOPS_PER_IMAGE = 2 * 4.09e9  # 4.09 GMACs fwd (torchvision count)
